"""Event-stream rasterization ops, TPU-native.

Re-designs the reference's CPU/Cython event encodings
(``/root/reference/dataloader/encodings.py``, ``cython_cnt2event/cnt2event.pyx``,
``cython_event_redistribute/event_redistribute.pyx``) as jit-able, static-shape
jnp scatter-add kernels.

Design choices vs the reference:

- **Static shapes + validity masks.** The reference works on ragged event
  lists and pads at collate time (``h5dataloader.py:248-263``). Under XLA every
  shape is static, so every op here takes a fixed-capacity event array plus a
  ``valid`` mask; invalid lanes contribute zero. This is what makes the whole
  data path jit-able and TPU-resident.
- **Channel-last layouts.** TPU convs want NHWC, so rasterized outputs are
  ``[H, W, C]`` (reference: ``[C, H, W]``).
- **Clean time binning by default.** The reference assigns events to temporal
  bins with an inclusive binary search that double-counts exact-boundary
  events (``encodings.py:176-181``). ``events_to_stack`` defaults to the
  standard half-open binning ``bin = floor((t - t0)/dt * B)`` — exact for the
  headline config (TIME_BINS=1) and sum-preserving — and offers
  ``binning='inclusive'`` for bit-exact reference parity when needed.

Events are a struct-of-arrays: ``xs, ys, ts, ps`` each ``[N]`` float32 (or
int for coords), ``ps in {-1, +1}``, ``ts`` normalized to ``[0, 1]`` by the
data pipeline (reference: ``base_dataset.py:32``).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


Array = jax.Array


def _valid_or_ones(valid: Optional[Array], n: int) -> Array:
    if valid is None:
        return jnp.ones((n,), dtype=jnp.float32)
    return valid.astype(jnp.float32)


def events_to_image(
    xs: Array,
    ys: Array,
    ps: Array,
    sensor_size: Tuple[int, int],
    valid: Optional[Array] = None,
    interpolation: Optional[str] = None,
) -> Array:
    """Scatter-add events into an ``[H, W]`` image.

    Equivalent of ``events_to_image_torch`` (reference ``encodings.py:30-75``):
    integer mode does ``img.index_put_((ys, xs), ps, accumulate=True)``;
    bilinear mode splats each event over its 4 neighbouring pixels weighted by
    the fractional offset (reference ``interpolate_to_image``, ``iwe.py:75-90``).

    Out-of-range events are dropped (contribute zero), matching the reference's
    clip mask.
    """
    h, w = sensor_size
    v = _valid_or_ones(valid, xs.shape[0])
    img = jnp.zeros((h, w), dtype=jnp.float32)

    if interpolation == "bilinear":
        px = jnp.floor(xs)
        py = jnp.floor(ys)
        dx = (xs - px).astype(jnp.float32)
        dy = (ys - py).astype(jnp.float32)
        pxi = px.astype(jnp.int32)
        pyi = py.astype(jnp.int32)
        vals = ps.astype(jnp.float32) * v
        for ox, oy, wgt in (
            (0, 0, (1.0 - dx) * (1.0 - dy)),
            (1, 0, dx * (1.0 - dy)),
            (0, 1, (1.0 - dx) * dy),
            (1, 1, dx * dy),
        ):
            xi = pxi + ox
            yi = pyi + oy
            inb = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
            wv = jnp.where(inb, wgt * vals, 0.0)
            img = img.at[jnp.clip(yi, 0, h - 1), jnp.clip(xi, 0, w - 1)].add(
                wv, mode="drop"
            )
        return img

    # Bounds-check the *float* coords before truncation: xs=-0.4 must be
    # dropped, not truncated onto column 0 (reference masks pre-.long()).
    inb = (xs >= 0) & (xs < w) & (ys >= 0) & (ys < h)
    xi = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
    yi = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
    vals = jnp.where(inb, ps.astype(jnp.float32) * v, 0.0)
    return img.at[yi, xi].add(vals, mode="drop")


def events_to_channels(
    xs: Array,
    ys: Array,
    ps: Array,
    sensor_size: Tuple[int, int],
    valid: Optional[Array] = None,
) -> Array:
    """Two-channel event-count image ``[H, W, 2]`` (pos, neg).

    Equivalent of reference ``encodings.py:289-304``: polarity +1 events count
    into channel 0, -1 events into channel 1; both channels are non-negative
    counts (the reference's ``ps * mask`` squares the ±1 polarity).
    """
    pos = jnp.where(ps > 0, 1.0, 0.0)
    neg = jnp.where(ps < 0, 1.0, 0.0)
    img_pos = events_to_image(xs, ys, pos, sensor_size, valid)
    img_neg = events_to_image(xs, ys, neg, sensor_size, valid)
    return jnp.stack([img_pos, img_neg], axis=-1)


def _normalized_bin_time(ts: Array, valid_f: Array) -> Tuple[Array, Array, Array]:
    """First/last valid timestamp and the window length (+eps)."""
    big = jnp.float32(jnp.inf)
    t0 = jnp.min(jnp.where(valid_f > 0, ts, big))
    t1 = jnp.max(jnp.where(valid_f > 0, ts, -big))
    t0 = jnp.where(jnp.isfinite(t0), t0, 0.0)
    t1 = jnp.where(jnp.isfinite(t1), t1, 0.0)
    dt = t1 - t0 + 1e-6
    return t0, t1, dt


def events_to_voxel(
    xs: Array,
    ys: Array,
    ts: Array,
    ps: Array,
    num_bins: int,
    sensor_size: Tuple[int, int],
    valid: Optional[Array] = None,
    round_ts: bool = False,
) -> Array:
    """Voxel grid ``[H, W, B]`` with temporal bilinear weights.

    Equivalent of reference ``events_to_voxel`` (``encodings.py:271-287``):
    ``w_b(t) = max(0, 1 - |t*(B-1) - b|)`` — ``ts`` must already be
    normalized to [0, 1].
    """
    v = _valid_or_ones(valid, xs.shape[0])
    tnorm = ts.astype(jnp.float32) * (num_bins - 1)
    if round_ts:
        tnorm = jnp.round(tnorm)
    bins = []
    for b in range(num_bins):
        weights = jnp.maximum(0.0, 1.0 - jnp.abs(tnorm - b))
        bins.append(
            events_to_image(xs, ys, ps.astype(jnp.float32) * weights, sensor_size, v)
        )
    return jnp.stack(bins, axis=-1)


def events_to_stack(
    xs: Array,
    ys: Array,
    ts: Array,
    ps: Array,
    num_bins: int,
    sensor_size: Tuple[int, int],
    valid: Optional[Array] = None,
    polarity: bool = False,
    binning: str = "half_open",
) -> Array:
    """Time-binned event stack.

    ``polarity=False`` → ``[H, W, B]`` signed counts per bin (equivalent of
    reference ``events_to_stack_no_polarity``, ``encodings.py:204-240``).
    ``polarity=True`` → ``[H, W, B, 2]`` split by polarity (equivalent of
    ``events_to_stack_polarity``, ``encodings.py:153-201``; reference layout
    ``[2, B, H, W]``).

    Bins span ``[t_first, t_last]`` of the *valid* events.
    ``binning='half_open'`` (default) assigns each event to exactly one bin
    (the clean partition — module docstring); ``binning='inclusive'``
    reproduces the reference's index-based bin membership — per bin, events
    in ``[searchsorted_left(tstart), searchsorted_right(tend))`` of the
    time-sorted stream, i.e. the CLOSED time interval ``[tstart, tend]``
    (``encodings.py:224-236``: its custom binary search returns ``l-1`` on a
    miss for ``side='right'``, and the ``+1`` there just compensates), which
    double-counts exact-boundary events into adjacent bins. Verified against
    the executed reference in ``tests/test_reference_parity_ops.py``.
    Residual divergence: when a bin edge exactly equals a RUN of duplicate
    timestamps, the reference's probe returns an arbitrary index inside the
    run (it tests ``t[l]``/``t[r]``/``t[mid]`` for equality) while
    searchsorted takes the whole run — a probe-path-dependent reference
    behavior no vectorized form can reproduce. Inclusive mode requires
    ``ts`` ascending over the valid lanes (true for stream windows).
    """
    assert binning in ("half_open", "inclusive"), binning
    h, w = sensor_size
    n = xs.shape[0]
    v = _valid_or_ones(valid, n)
    tsf = ts.astype(jnp.float32)

    inb = (xs >= 0) & (xs < w) & (ys >= 0) & (ys < h)
    xi = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
    yi = jnp.clip(ys.astype(jnp.int32), 0, h - 1)

    if binning == "inclusive":
        t0, _, dt = _normalized_bin_time(tsf, v)
        delta = dt / num_bins
        # padded lanes pushed past every bin end; valid prefix stays sorted
        ts_eff = jnp.where(v > 0, tsf, jnp.inf)
        starts = t0 + delta * jnp.arange(num_bins)
        begs = jnp.searchsorted(ts_eff, starts, side="left")
        # The reference's custom binary search returns r (== l-1) on a miss
        # for side='right', then adds 1 (encodings.py:229-230) — net effect
        # is exactly searchsorted-right: the closed interval [tstart, tend].
        ends = jnp.searchsorted(ts_eff, starts + delta, side="right")
        idx = jnp.arange(n)
        # [N, B] membership — an event may belong to adjacent bins
        member = (idx[:, None] >= begs[None, :]) & (idx[:, None] < ends[None, :])

        # reference degenerate-window guard (encodings.py:219-220): all-zero
        # valid timestamps or <= 3 valid events -> all-zero stack.
        # Deliberate deviation: the reference evaluates len(ts) over its
        # (unpadded) cloud, so "number of events" here is the VALID lane
        # count — a padded cloud with 1-3 real events zeroes out where the
        # reference fed the same padded rows would rasterize. The valid-mask
        # semantics are the faithful translation (the reference never sees
        # padding).
        n_valid = v.sum()
        ts_sum = jnp.where(v > 0, tsf, 0.0).sum()
        alive = jnp.where((ts_sum == 0) | (n_valid <= 3), 0.0, 1.0)

        if polarity:
            out = jnp.zeros((h, w, num_bins, 2), dtype=jnp.float32)
            pos = jnp.where((ps > 0) & inb, v, 0.0)
            neg = jnp.where((ps < 0) & inb, v, 0.0)
            for b in range(num_bins):
                m = member[:, b]
                out = out.at[yi, xi, b, 0].add(jnp.where(m, pos, 0.0), mode="drop")
                out = out.at[yi, xi, b, 1].add(jnp.where(m, neg, 0.0), mode="drop")
            return out * alive
        vals = jnp.where(inb, ps.astype(jnp.float32) * v, 0.0)
        out = jnp.zeros((h, w, num_bins), dtype=jnp.float32)
        for b in range(num_bins):
            out = out.at[yi, xi, b].add(
                jnp.where(member[:, b], vals, 0.0), mode="drop"
            )
        return out * alive

    t0, _, dt = _normalized_bin_time(tsf, v)
    rel = (tsf - t0) / dt
    bin_idx = jnp.clip(jnp.floor(rel * num_bins).astype(jnp.int32), 0, num_bins - 1)

    if polarity:
        out = jnp.zeros((h, w, num_bins, 2), dtype=jnp.float32)
        pos = jnp.where((ps > 0) & inb, v, 0.0)
        neg = jnp.where((ps < 0) & inb, v, 0.0)
        out = out.at[yi, xi, bin_idx, 0].add(pos, mode="drop")
        out = out.at[yi, xi, bin_idx, 1].add(neg, mode="drop")
        return out

    vals = jnp.where(inb, ps.astype(jnp.float32) * v, 0.0)
    out = jnp.zeros((h, w, num_bins), dtype=jnp.float32)
    return out.at[yi, xi, bin_idx].add(vals, mode="drop")


def tile_activity(counts: Array, tile: int = 8) -> Array:
    """Per-tile activity sums of a count image — the activity-mask plane's
    device-side derivation (docs/PERF.md "activity-sparse compute").

    ``counts``: ``[H, W, ...]`` non-negative per-pixel event counts (any
    trailing channel axes are folded in). Returns ``[ceil(H/tile),
    ceil(W/tile)]`` f32 per-tile summed counts; a tile is ACTIVE iff its
    sum is ``> 0``. The reduction is exact (counts are small integers in
    f32, far below the 2^24 mantissa bound), so this twin and
    :func:`esr_tpu.data.np_encodings.tile_activity_np` agree
    bit-for-bit — pinned by ``tests/test_encodings.py``.

    ``tile`` defaults to the flagship model's ``down_scale`` (8): one
    activity cell per DCN-bottleneck pixel.
    """
    if tile < 1:
        raise ValueError(f"tile must be >= 1, got {tile}")
    h, w = counts.shape[0], counts.shape[1]
    c = counts.reshape(h, w, -1).sum(axis=-1)
    ht = -(-h // tile)
    wt = -(-w // tile)
    c = jnp.pad(c, ((0, ht * tile - h), (0, wt * tile - w)))
    return c.reshape(ht, tile, wt, tile).sum(axis=(1, 3)).astype(jnp.float32)


def activity_fraction(act: Array) -> Array:
    """Fraction of active tiles of a :func:`tile_activity` map (any
    shape; scalar f32 in [0, 1]) — the scheduler-gating statistic."""
    return (act > 0).astype(jnp.float32).mean()


def events_to_channels_activity(
    xs: Array,
    ys: Array,
    ps: Array,
    sensor_size: Tuple[int, int],
    valid: Optional[Array] = None,
    tile: int = 8,
) -> Tuple[Array, Array]:
    """Count image + per-tile activity sidecar in one pass: the encoder
    already sums per-pixel event counts, so the activity map is a free
    per-tile reduction of the counts it just built (never a second pass
    over the events). Returns ``([H, W, 2] counts, [Ht, Wt] activity)``."""
    cnt = events_to_channels(xs, ys, ps, sensor_size, valid)
    return cnt, tile_activity(cnt, tile)


def events_to_mask(
    xs: Array,
    ys: Array,
    ps: Array,
    sensor_size: Tuple[int, int],
    valid: Optional[Array] = None,
) -> Array:
    """Binary ``[H, W]`` activity mask (reference ``encodings.py:310-327``)."""
    img = events_to_image(xs, ys, jnp.abs(ps.astype(jnp.float32)), sensor_size, valid)
    return (img > 0).astype(jnp.float32)


def events_polarity_mask(ps: Array) -> Array:
    """``[N, 2]`` one-hot polarity mask (reference ``encodings.py:330-341``)."""
    pos = jnp.where(ps > 0, ps, 0.0)
    neg = jnp.where(ps < 0, -ps, 0.0)
    return jnp.stack([pos, neg], axis=-1).astype(jnp.float32)


def get_hot_event_mask(
    event_rate: Array,
    idx: int,
    max_px: int = 100,
    min_obvs: int = 5,
    max_rate: float = 0.8,
) -> Array:
    """Binary mask zeroing hot pixels (reference ``encodings.py:348-363``).

    The reference iteratively pops the argmax pixel up to ``max_px`` times,
    stopping at the first rate <= ``max_rate``. Vectorized equivalent: zero
    exactly the pixels that are simultaneously (a) among the ``max_px``
    largest rates and (b) above ``max_rate``. Identical except for exact-tie
    orderings at the cutoff rank.
    """
    h, w = event_rate.shape
    flat = event_rate.reshape(-1)
    k = min(max_px, flat.shape[0])
    _, top_idx = jax.lax.top_k(flat, k)
    in_topk = jnp.zeros((flat.shape[0],), dtype=bool).at[top_idx].set(True)
    hot = in_topk & (flat > max_rate)
    mask = jnp.where(hot, 0.0, 1.0).reshape(h, w)
    return jax.lax.cond(idx > min_obvs, lambda: mask, lambda: jnp.ones((h, w)))


# ---------------------------------------------------------------------------
# Inverse rasterization: dense grids -> synthetic event lists
# ---------------------------------------------------------------------------


def _counts_to_events(
    counts: Array,
    xs_of: Array,
    ys_of: Array,
    ps_of: Array,
    t_start: Array,
    t_end: Array,
    capacity: int,
) -> Tuple[Array, Array]:
    """Core of the inverse ops: expand per-cell counts into an event list.

    ``counts [M]`` non-negative integer counts per flat cell; ``xs_of/ys_of/
    ps_of/t_start/t_end [M]`` per-cell attributes. Produces up to ``capacity``
    events; event ``r`` of a cell with count ``c`` gets timestamp
    ``t_start + (t_end - t_start) * r/(c-1)`` (matching ``np.linspace`` with
    endpoints, reference ``cnt2event.pyx:74``), then the whole list is stably
    sorted by time, matching the reference's global sort.

    If the total count exceeds ``capacity``, the first ``capacity`` events in
    construction (scan) order are kept — a biased truncation (e.g. cnt2event's
    polarity-major order drops negatives first). Callers must size capacity
    for the worst case; ``valid.sum() == capacity`` signals possible clipping.

    Returns ``(events [capacity, 4] as [x, y, t, p], valid [capacity])``.
    """
    # Negative counts (e.g. a model predicting -0.9) would make the cumsum
    # non-monotonic and corrupt the searchsorted cell assignment.
    counts = jnp.maximum(counts.astype(jnp.int32), 0)
    cum = jnp.cumsum(counts)
    total = cum[-1]
    ranks = jnp.arange(capacity, dtype=jnp.int32)
    # Cell owning global event rank r: first cell whose cumsum exceeds r.
    cell = jnp.searchsorted(cum, ranks, side="right").astype(jnp.int32)
    cell = jnp.clip(cell, 0, counts.shape[0] - 1)
    in_range = ranks < total
    start = cum[cell] - counts[cell]
    r_in_cell = (ranks - start).astype(jnp.float32)
    c = counts[cell].astype(jnp.float32)
    frac = jnp.where(c > 1, r_in_cell / jnp.maximum(c - 1.0, 1.0), 0.0)
    t = t_start[cell] + (t_end[cell] - t_start[cell]) * frac
    x = xs_of[cell].astype(jnp.float32)
    y = ys_of[cell].astype(jnp.float32)
    p = ps_of[cell].astype(jnp.float32)

    t_sortkey = jnp.where(in_range, t, jnp.inf)
    order = jnp.argsort(t_sortkey, stable=True)
    ev = jnp.stack([x, y, t, p], axis=-1)[order]
    valid = in_range[order]
    ev = jnp.where(valid[:, None], ev, 0.0)
    return ev, valid


def cnt2event(cnt: Array, capacity: int) -> Tuple[Array, Array]:
    """Inverse rasterization: count image -> synthetic event list.

    TPU-native equivalent of the Cython ``cnt2event`` kernel
    (``cython_cnt2event/cnt2event.pyx:18-116``, linear mode): every pixel with
    rounded count ``c`` in the pos/neg channel emits ``c`` events at that
    pixel with timestamps ``linspace(0, 1, c)`` and polarity ±1; the list is
    globally time-sorted (positives before negatives at equal timestamps,
    matching the reference's stable sort over pos-then-neg construction).

    ``cnt``: ``[H, W, 2]`` (pos, neg). Returns ``([capacity, 4] events as
    [x, y, t, p], [capacity] valid)`` — fixed capacity + mask replaces the
    reference's ragged output. Random timestamp mode is intentionally not
    ported (fixed-seed numpy inside a kernel; linear mode is what parity
    requires).
    """
    h, w, _ = cnt.shape
    counts = jnp.round(cnt).astype(jnp.int32)
    ys, xs = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing="ij")
    # Polarity-major flattening: all positive cells first, then negative,
    # mirroring the reference's construction order before the time sort.
    xs_of = jnp.concatenate([xs.reshape(-1), xs.reshape(-1)])
    ys_of = jnp.concatenate([ys.reshape(-1), ys.reshape(-1)])
    m = h * w
    ps_of = jnp.concatenate([jnp.ones((m,)), -jnp.ones((m,))])
    flat_counts = jnp.concatenate(
        [counts[..., 0].reshape(-1), counts[..., 1].reshape(-1)]
    )
    zeros = jnp.zeros((2 * m,), dtype=jnp.float32)
    ones = jnp.ones((2 * m,), dtype=jnp.float32)
    return _counts_to_events(flat_counts, xs_of, ys_of, ps_of, zeros, ones, capacity)


def event_redistribute(stack: Array, capacity: int) -> Tuple[Array, Array]:
    """Time-binned stack -> event list with per-bin time bases.

    TPU-native equivalent of ``event_redistribute_NoPolarityStack``
    (``cython_event_redistribute/event_redistribute.pyx:88-154``, linear
    mode): a cell in bin ``b`` of ``num_bins`` with rounded signed count ``c``
    emits ``|c|`` events of polarity ``sign(c)`` with timestamps
    ``linspace(b/B + 1/(100B), (b+1)/B, |c|)``.

    ``stack``: ``[H, W, B]`` signed counts (our channel-last layout of the
    reference's ``[B, Y, X]``). Returns fixed-capacity events + valid mask.
    """
    h, w, num_bins = stack.shape
    counts = jnp.round(stack)
    ys, xs = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing="ij")
    xs_of = jnp.tile(xs.reshape(-1), num_bins)
    ys_of = jnp.tile(ys.reshape(-1), num_bins)
    bin_of = jnp.repeat(jnp.arange(num_bins), h * w)
    # [H,W,B] -> bin-major flat order to mirror np.nonzero's scan order over
    # the reference's [B, Y, X] layout.
    flat = jnp.moveaxis(counts, -1, 0).reshape(-1)
    ps_of = jnp.where(flat >= 0, 1.0, -1.0)
    t_start = bin_of / num_bins + 1.0 / (100.0 * num_bins)
    t_end = (bin_of + 1.0) / num_bins
    return _counts_to_events(
        jnp.abs(flat).astype(jnp.int32),
        xs_of,
        ys_of,
        ps_of,
        t_start.astype(jnp.float32),
        t_end.astype(jnp.float32),
        capacity,
    )


def event_redistribute_polarity(stack: Array, capacity: int) -> Tuple[Array, Array]:
    """Polarity variant (reference ``event_redistribute.pyx:17-86``).

    ``stack``: ``[H, W, B, 2]`` non-negative counts (pos, neg). Cells in the
    pos channel emit +1 events, neg channel -1 events, same per-bin time base
    as :func:`event_redistribute`.
    """
    h, w, num_bins, _ = stack.shape
    counts = jnp.round(stack)
    ys, xs = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing="ij")
    m = h * w
    # Reference scan order over [P, C, Y, X]: polarity-major, then bin.
    xs_of = jnp.tile(xs.reshape(-1), 2 * num_bins)
    ys_of = jnp.tile(ys.reshape(-1), 2 * num_bins)
    bin_of = jnp.tile(jnp.repeat(jnp.arange(num_bins), m), 2)
    pol_of = jnp.repeat(jnp.array([1.0, -1.0]), num_bins * m)
    # [H,W,B,P] -> [P,B,H,W] flat
    flat = jnp.transpose(counts, (3, 2, 0, 1)).reshape(-1)
    t_start = bin_of / num_bins + 1.0 / (100.0 * num_bins)
    t_end = (bin_of + 1.0) / num_bins
    return _counts_to_events(
        flat.astype(jnp.int32),
        xs_of,
        ys_of,
        pol_of,
        t_start.astype(jnp.float32),
        t_end.astype(jnp.float32),
        capacity,
    )


# Batched variants (vmap over leading batch dim).
cnt2event_batch = jax.vmap(cnt2event, in_axes=(0, None))
event_redistribute_batch = jax.vmap(event_redistribute, in_axes=(0, None))
event_redistribute_polarity_batch = jax.vmap(
    event_redistribute_polarity, in_axes=(0, None)
)


def stack2cnt(stack: Array) -> Array:
    """Time-binned stack -> 2-channel count image (reference
    ``encodings.py:652-670``): round, split signed counts by sign, sum over
    bins. ``stack``: ``[..., H, W, TB]`` -> ``[..., H, W, 2]``
    (reference layout ``[B, TB, H, W]`` -> ``[B, 2, H, W]``)."""
    s = jnp.round(stack)
    pos = jnp.where(s > 0, s, 0.0).sum(axis=-1)
    neg = (-jnp.where(s < 0, s, 0.0)).sum(axis=-1)
    return jnp.stack([pos, neg], axis=-1)


def event_restore(events: Array, resolution: Tuple[int, int]) -> Array:
    """Denormalize an event cloud (reference ``encodings.py:580-601``):
    ``[B, N, 4]`` (x, y, t, p) with x/y in [0,1) -> pixel coords, polarity
    snapped to exactly ±1 (zero-padded lanes stay 0)."""
    h, w = resolution
    x = events[..., 0] * w
    y = events[..., 1] * h
    t = events[..., 2]
    p = jnp.sign(events[..., 3])
    return jnp.stack([x, y, t, p], axis=-1)


def event_conversion(
    event_list: Array,
    time_bins: int,
    resolution: Tuple[int, int],
    time_bins_voxel: Optional[int] = None,
    valid: Optional[Array] = None,
) -> Dict[str, Array]:
    """Batched event clouds -> every dense encoding at once (reference
    ``encodings.py:536-577``).

    ``event_list``: ``[B, N, 4]`` (x, y, t, p); ``valid``: optional
    ``[B, N]`` lane mask for padded clouds (the reference instead carries
    ragged lists). Returns ``{'e_cnt': [B,H,W,2], 'e_voxel': [B,H,W,TBv],
    'e_stack': [B,H,W,TB]}``; the stack uses the reference's inclusive
    binning, and each cloud is time-sorted first exactly like the
    reference's ``sort_events``. ``ts`` must already be normalized to [0,1]
    (true for formatted windows). The reference's MinkowskiEngine variant
    ``sparse2event`` (``:604-649``) is dead code there (the ME import is
    commented out) and has no equivalent here.
    """
    if time_bins_voxel is None:
        time_bins_voxel = time_bins
    v = (
        jnp.ones(event_list.shape[:2], jnp.float32)
        if valid is None
        else valid.astype(jnp.float32)
    )

    def one(entry, vb):
        # stable time sort with padded lanes pushed to the end
        order = jnp.argsort(jnp.where(vb > 0, entry[:, 2], jnp.inf), stable=True)
        e = entry[order]
        vs = vb[order]
        xs, ys, ts, ps = e[:, 0], e[:, 1], e[:, 2], e[:, 3]
        return (
            events_to_channels(xs, ys, ps, resolution, valid=vs),
            events_to_voxel(xs, ys, ts, ps, time_bins_voxel, resolution, valid=vs),
            events_to_stack(
                xs, ys, ts, ps, time_bins, resolution, valid=vs,
                binning="inclusive",
            ),
        )

    cnt, voxel, stack = jax.vmap(one)(event_list, v)
    return {"e_cnt": cnt, "e_voxel": voxel, "e_stack": stack}


def normalize_events(
    xs: Array, ys: Array, sensor_size: Tuple[int, int]
) -> Tuple[Array, Array]:
    """Normalize event coords to [0, 1) (reference ``h5dataset.py:508-518``)."""
    h, w = sensor_size
    return xs.astype(jnp.float32) / w, ys.astype(jnp.float32) / h


def scale_event_coords(
    xs_norm: Array, ys_norm: Array, target_size: Tuple[int, int]
) -> Tuple[Array, Array]:
    """Renormalize [0,1) coords onto a target grid — the SR input transform.

    Reference ``create_scaled_encoding`` (``h5dataset.py:520-537``): LR event
    coordinates are mapped onto the HR grid (leaving holes), where they are
    re-rasterized. Truncation (``.long()``) matches the reference.
    """
    h, w = target_size
    return (
        jnp.floor(xs_norm * w).astype(jnp.int32),
        jnp.floor(ys_norm * h).astype(jnp.int32),
    )


def make_device_encoder(gt_resolution: Tuple[int, int]):
    """Build the jitted on-device batch encoder: raw event windows in,
    dense count images out — host rasterization moved off the critical
    path (``dataset.encode: device``, docs/CONFIG.md).

    The host ships fixed-capacity padded event windows (~4 floats/event
    vs a dense ``[H, W, 2]`` image per frame) and the device scatter-adds
    them inside the consuming jit. Consumes the raw-event batch contract
    ``{"inp_events" [B, L, N, 4] (coords normalized to [0,1)),
    "inp_valid" [B, L, N], "gt_events" [B, L, Ng, 4] (raw GT-grid
    coords), "gt_valid"}`` and produces the dense ``{"inp", "gt"}``
    streams the train/eval steps expect.

    Per-event math is the PR-12 jnp twin of the host path
    (``np_encodings``): ``scale_event_coords`` + ``events_to_channels``
    for the input rung, plain ``events_to_channels`` for GT — so the
    integer count images are BITWISE equal to host encoding (pinned in
    tier-1), and ``encode: device|host`` is a pure placement knob, never
    a numerics knob. Counts accumulate in f32 regardless of
    ``trainer.precision``; the mixed-precision cast happens inside the
    train step like every other input stream.
    """
    kh, kw = gt_resolution

    def _inp_one(ev, valid):
        xs, ys = scale_event_coords(ev[:, 0], ev[:, 1], (kh, kw))
        return events_to_channels(xs, ys, ev[:, 3], (kh, kw), valid=valid)

    def _gt_one(ev, valid):
        return events_to_channels(
            ev[:, 0], ev[:, 1], ev[:, 3], (kh, kw), valid=valid
        )

    vmap2 = lambda f: jax.vmap(jax.vmap(f))  # over B, L

    def encode(batch: Dict[str, Array]) -> Dict[str, Array]:
        return {
            "inp": vmap2(_inp_one)(batch["inp_events"], batch["inp_valid"]),
            "gt": vmap2(_gt_one)(batch["gt_events"], batch["gt_valid"]),
        }

    return encode
