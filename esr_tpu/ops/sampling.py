"""Bilinear grid sampling (torch ``F.grid_sample`` semantics), jnp."""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def grid_sample(
    img: Array, grid: Array, align_corners: bool = False
) -> Array:
    """Bilinear sample with zero padding.

    ``img``: ``[B, H, W, C]``; ``grid``: ``[B, Ho, Wo, 2]`` as (x, y) in
    [-1, 1]. Matches ``torch.nn.functional.grid_sample(mode='bilinear',
    padding_mode='zeros')``; ``align_corners=False`` (torch's default) maps
    -1/+1 to the outer pixel *edges*, ``True`` to the outer pixel centers.
    """
    b, h, w, c = img.shape
    gx, gy = grid[..., 0], grid[..., 1]
    if align_corners:
        x = (gx + 1.0) * (w - 1) / 2.0
        y = (gy + 1.0) * (h - 1) / 2.0
    else:
        x = ((gx + 1.0) * w - 1.0) / 2.0
        y = ((gy + 1.0) * h - 1.0) / 2.0

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    out = 0.0
    for ox, oy in ((0, 0), (1, 0), (0, 1), (1, 1)):
        xi = x0 + ox
        yi = y0 + oy
        wgt = (1.0 - jnp.abs(x - xi)) * (1.0 - jnp.abs(y - yi))
        inb = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        xc = jnp.clip(xi.astype(jnp.int32), 0, w - 1)
        yc = jnp.clip(yi.astype(jnp.int32), 0, h - 1)
        vals = jax.vmap(lambda im, yy, xx: im[yy, xx])(img, yc, xc)
        out = out + jnp.where((inb & jnp.isfinite(wgt))[..., None], wgt[..., None] * vals, 0.0)
    return out
