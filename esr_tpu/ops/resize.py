"""Image resizing with exact PyTorch semantics, as MXU matmuls.

The reference leans on ``torch.nn.functional.interpolate`` with
``align_corners=False`` — bilinear inside ``UpsampleConvLayer``
(``/root/reference/models/submodules.py:290``) and bicubic for the SR input
ladder and the bicubic baseline (``h5dataset.py:341``,
``train_ours_cnt_seq.py:225``, ``infer_ours_cnt.py:78``).

``jax.image.resize`` is NOT numerically equivalent: its cubic kernel uses the
Keys coefficient a=-0.5 while torch uses a=-0.75, and metric parity (PSNR/SSIM
vs the bicubic baseline) depends on matching torch. So we build separable
interpolation weight matrices (with torch's half-pixel source mapping and
border replication) at trace time in numpy; the resize itself is then two
dense matmuls — the ideal shape for the TPU MXU, and XLA folds the constant
weight matrices.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _source_coords(in_size: int, out_size: int) -> np.ndarray:
    """Half-pixel source coordinates (torch ``align_corners=False``)."""
    scale = in_size / out_size
    return (np.arange(out_size, dtype=np.float64) + 0.5) * scale - 0.5


def _cubic_kernel(x: np.ndarray, a: float = -0.75) -> np.ndarray:
    """Keys cubic convolution kernel; torch uses a=-0.75."""
    ax = np.abs(x)
    ax2 = ax * ax
    ax3 = ax2 * ax
    w = np.where(
        ax <= 1.0,
        (a + 2.0) * ax3 - (a + 3.0) * ax2 + 1.0,
        np.where(ax < 2.0, a * ax3 - 5.0 * a * ax2 + 8.0 * a * ax - 4.0 * a, 0.0),
    )
    return w


@functools.lru_cache(maxsize=None)
def _interp_matrix(in_size: int, out_size: int, mode: str) -> np.ndarray:
    """``[out_size, in_size]`` row-stochastic interpolation matrix."""
    if mode == "nearest":
        # torch 'nearest' uses floor(dst * scale) (legacy, no half-pixel).
        src = np.floor(np.arange(out_size) * (in_size / out_size)).astype(np.int64)
        src = np.clip(src, 0, in_size - 1)
        mat = np.zeros((out_size, in_size), dtype=np.float32)
        mat[np.arange(out_size), src] = 1.0
        return mat

    src = _source_coords(in_size, out_size)
    mat = np.zeros((out_size, in_size), dtype=np.float64)
    if mode == "bilinear":
        base = np.floor(src).astype(np.int64)
        frac = src - base
        for tap, wgt in ((0, 1.0 - frac), (1, frac)):
            idx = np.clip(base + tap, 0, in_size - 1)
            np.add.at(mat, (np.arange(out_size), idx), wgt)
    elif mode == "bicubic":
        base = np.floor(src).astype(np.int64)
        frac = src - base
        for tap in range(-1, 3):
            wgt = _cubic_kernel(frac - tap)
            idx = np.clip(base + tap, 0, in_size - 1)
            np.add.at(mat, (np.arange(out_size), idx), wgt)
    else:
        raise ValueError(f"unsupported resize mode: {mode}")
    return mat.astype(np.float32)


def interpolate(
    x: jax.Array,
    size: Tuple[int, int],
    mode: str = "bilinear",
) -> jax.Array:
    """Resize ``[..., H, W, C]`` to ``[..., size[0], size[1], C]``.

    Numerically matches ``torch.nn.functional.interpolate(...,
    align_corners=False)`` for ``bilinear`` / ``bicubic`` / ``nearest``
    (channel-last here; the reference is NCHW).
    """
    h_in, w_in = x.shape[-3], x.shape[-2]
    h_out, w_out = size
    if (h_in, w_in) == (h_out, w_out):
        return x
    # f32 accumulation is required: metric parity vs torch breaks under the
    # TPU default (bf16-ish) matmul precision.
    mh = jnp.asarray(_interp_matrix(h_in, h_out, mode))
    mw = jnp.asarray(_interp_matrix(w_in, w_out, mode))
    x = jnp.einsum("oh,...hwc->...owc", mh, x, precision="highest")
    x = jnp.einsum("ow,...hwc->...hoc", mw, x, precision="highest")
    return x


def interpolate_scale(x: jax.Array, scale: int, mode: str = "bilinear") -> jax.Array:
    """Scale-factor form of :func:`interpolate`."""
    h, w = x.shape[-3], x.shape[-2]
    return interpolate(x, (h * scale, w * scale), mode)
