from . import dcn
from . import encodings
from . import psroi
from . import resize
from esr_tpu.ops.psroi import deform_psroi_pooling

__all__ = ["dcn", "encodings", "psroi", "resize", "deform_psroi_pooling"]
