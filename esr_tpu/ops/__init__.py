from . import encodings
from . import resize
from . import dcn
