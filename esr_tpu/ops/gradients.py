"""Spatial gradient ops (reference ``myutils/gradients.py``)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

_SOBEL_X = jnp.array(
    [[-1.0, 0.0, 1.0], [-2.0, 0.0, 2.0], [-1.0, 0.0, 1.0]]
)
_SOBEL_Y = jnp.array(
    [[-1.0, -2.0, -1.0], [0.0, 0.0, 0.0], [1.0, 2.0, 1.0]]
)


def sobel(x: Array) -> Tuple[Array, Array]:
    """Normalized Sobel gradients with replication padding.

    Equivalent of the reference ``Sobel`` module (``gradients.py:7-33``):
    channels are folded into the batch, the input is replication-padded by 1,
    and the 3x3 Sobel responses are divided by 8.

    ``x``: ``[B, H, W, C]`` -> ``(gradx, grady)`` each ``[B, H, W, C]``.
    """
    b, h, w, c = x.shape
    flat = jnp.moveaxis(x, -1, 1).reshape(b * c, h, w)
    padded = jnp.pad(flat, ((0, 0), (1, 1), (1, 1)), mode="edge")

    def conv(img, k):
        return jax.lax.conv_general_dilated(
            img[:, :, :, None],
            k[:, :, None, None],
            window_strides=(1, 1),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )[..., 0]

    gx = conv(padded, _SOBEL_X) / 8.0
    gy = conv(padded, _SOBEL_Y) / 8.0
    gx = jnp.moveaxis(gx.reshape(b, c, h, w), 1, -1)
    gy = jnp.moveaxis(gy.reshape(b, c, h, w), 1, -1)
    return gx, gy
