"""Image-of-warped-events (IWE) utilities, TPU-native.

Rebuilds ``/root/reference/myutils/iwe.py`` as jit-able static-shape jnp.
Events are ``[B, N, 4]`` rows ``(ts, y, x, p)`` — the layout the reference
actually indexes (``iwe.py:40``: coords are columns 1:3, ts is column 0,
despite the docstring). ``ts`` normalized to [0, 1].

Padded (invalid) event lanes are handled with an explicit ``valid`` mask that
zeroes their interpolation weights — the static-shape replacement for the
reference's ragged lists.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def purge_unfeasible(
    coords: Array, res: Tuple[int, int]
) -> Tuple[Array, Array]:
    """Zero out-of-bounds warped locations (reference ``iwe.py:4-17``).

    ``coords``: ``[B, M, 2]`` as (y, x). Returns masked coords and the
    ``[B, M, 1]`` keep-mask.
    """
    h, w = res
    y, x = coords[..., 0:1], coords[..., 1:2]
    mask = ((y >= 0) & (y < h) & (x >= 0) & (x < w)).astype(coords.dtype)
    return coords * mask, mask


def get_interpolation(
    events: Array,
    flow: Array,
    tref: float,
    res: Tuple[int, int],
    flow_scaling: float,
    round_idx: bool = False,
) -> Tuple[Array, Array]:
    """Warp events along per-event flow to ``tref`` and compute scatter
    indices + bilinear weights (reference ``iwe.py:20-72``).

    ``events``: ``[B, N, 4]`` (ts, y, x, p); ``flow``: ``[B, N, 2]`` per-event
    flow as (y, x) components. Returns flat indices ``[B, M, 1]`` (row-major
    ``y * W + x``) and weights ``[B, M, 1]``; M = N for ``round_idx`` else 4N
    (the four bilinear taps, tap-major like the reference's ``torch.cat``).
    """
    h, w = res
    warped = events[:, :, 1:3] + (tref - events[:, :, 0:1]) * flow * flow_scaling

    if round_idx:
        idx = jnp.round(warped)
        weights = jnp.ones_like(idx)
    else:
        top_y = jnp.floor(warped[:, :, 0:1])
        bot_y = top_y + 1
        left_x = jnp.floor(warped[:, :, 1:2])
        right_x = left_x + 1
        idx = jnp.concatenate(
            [
                jnp.concatenate([top_y, left_x], axis=2),
                jnp.concatenate([top_y, right_x], axis=2),
                jnp.concatenate([bot_y, left_x], axis=2),
                jnp.concatenate([bot_y, right_x], axis=2),
            ],
            axis=1,
        )
        warped4 = jnp.concatenate([warped] * 4, axis=1)
        weights = jnp.maximum(0.0, 1.0 - jnp.abs(warped4 - idx))

    idx, mask = purge_unfeasible(idx, res)
    weights = jnp.prod(weights, axis=-1, keepdims=True) * mask
    flat = idx[:, :, 0:1] * w + idx[:, :, 1:2]
    return flat, weights


def interpolate(
    idx: Array,
    weights: Array,
    res: Tuple[int, int],
    polarity_mask: Optional[Array] = None,
) -> Array:
    """Scatter-add warped events into a ``[B, H, W, 1]`` image
    (reference ``iwe.py:75-90``; reference layout ``[B, 1, H, W]``)."""
    h, w = res
    if polarity_mask is not None:
        weights = weights * polarity_mask
    b = idx.shape[0]
    flat_idx = jnp.clip(idx[..., 0].astype(jnp.int32), 0, h * w - 1)
    img = jnp.zeros((b, h * w), weights.dtype)
    img = jax.vmap(lambda im, ii, ww: im.at[ii].add(ww))(
        img, flat_idx, weights[..., 0]
    )
    return img.reshape(b, h, w, 1)


def gather_event_flow(flow_map: Array, events: Array) -> Array:
    """Per-event flow vectors from a dense map (reference ``iwe.py:106-117``).

    ``flow_map``: ``[B, H, W, 2]`` as (x, y) channels — matching the
    reference's channel order where channel 0 is horizontal. ``events``:
    ``[B, N, 4]`` (ts, y, x, p). Returns ``[B, N, 2]`` per-event (y, x)
    flow... NOTE: the reference gathers (vertical, horizontal) = channels
    (1, 0) and warps coords (y, x) with that order; we return the same
    (y-component, x-component) layout.
    """
    b, h, w, _ = flow_map.shape
    yi = jnp.clip(events[:, :, 1].astype(jnp.int32), 0, h - 1)
    xi = jnp.clip(events[:, :, 2].astype(jnp.int32), 0, w - 1)
    fy = jax.vmap(lambda m, y, x: m[y, x, 1])(flow_map, yi, xi)
    fx = jax.vmap(lambda m, y, x: m[y, x, 0])(flow_map, yi, xi)
    return jnp.stack([fy, fx], axis=-1)


def deblur_events(
    flow_map: Array,
    event_list: Array,
    res: Tuple[int, int],
    flow_scaling: float = 128,
    round_idx: bool = True,
    polarity_mask: Optional[Array] = None,
    valid: Optional[Array] = None,
) -> Array:
    """Motion-compensate events into a sharp IWE (reference ``iwe.py:93-127``).

    ``flow_map``: ``[B, H, W, 2]``; ``event_list``: ``[B, N, 4]`` (ts, y, x,
    p); ``valid``: ``[B, N]`` lane mask. Returns ``[B, H, W, 1]``.
    """
    event_flow = gather_event_flow(flow_map, event_list)
    fw_idx, fw_weights = get_interpolation(
        event_list, event_flow, 1, res, flow_scaling, round_idx=round_idx
    )
    reps = 1 if round_idx else 4
    if valid is not None:
        v = valid.astype(fw_weights.dtype)[:, :, None]
        fw_weights = fw_weights * jnp.concatenate([v] * reps, axis=1)
    if polarity_mask is not None and not round_idx:
        polarity_mask = jnp.concatenate([polarity_mask] * 4, axis=1)
    return interpolate(fw_idx, fw_weights, res, polarity_mask=polarity_mask)


def compute_pol_iwe(
    flow_map: Array,
    event_list: Array,
    res: Tuple[int, int],
    pos_mask: Array,
    neg_mask: Array,
    flow_scaling: float = 128,
    round_idx: bool = True,
    valid: Optional[Array] = None,
) -> Array:
    """Per-polarity IWE ``[B, H, W, 2]`` (reference ``iwe.py:130-151``)."""
    iwe_pos = deblur_events(
        flow_map, event_list, res, flow_scaling, round_idx, pos_mask, valid
    )
    iwe_neg = deblur_events(
        flow_map, event_list, res, flow_scaling, round_idx, neg_mask, valid
    )
    return jnp.concatenate([iwe_pos, iwe_neg], axis=-1)
