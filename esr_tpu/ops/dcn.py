"""Modulated deformable convolution (DCNv2), TPU-native.

Replaces the reference's CUDA extension (``/root/reference/models/DCNv2/src/
cuda/dcn_v2_cuda.cu:20-95`` and ``dcn_v2_im2col_cuda.cu``) with a gather-based
jnp formulation:

- per output pixel / kernel tap / deformable group, compute the fractional
  sampling position (base grid + tap offset + learned offset),
- 4-tap bilinear gather with zero padding outside the image (matching
  ``dmcn_im2col_bilinear_cuda``'s boundary handling),
- multiply by the sigmoid modulation mask,
- contract the gathered columns with the conv weight in one einsum, which XLA
  lowers to an MXU matmul over ``[B*Ho*Wo, K*Cin] x [K*Cin, Cout]``.

The backward pass comes from XLA autodiff: the transpose of the bilinear
gather is exactly the reference's atomicAdd col2im scatter
(``dcn_v2_im2col_cuda.cu:56-123``), so no custom VJP is needed for
correctness. A fused Pallas kernel is the planned fast path.

Offset/mask channel layout: the reference's ``chunk(3) + cat`` scheme
(``dcn_v2.py:180-182``) produces a learned permutation of the CUDA kernel's
``[g, 2*K]`` interleaved layout; since ``conv_offset_mask`` is zero-initialized
and learned, the exact permutation is not semantically meaningful. We define
the clean layout ``offsets [..., dg, K, 2] = (dy, dx)``, ``mask [..., dg, K]``.

All tensors are channel-last (NHWC / HWIO), the TPU-native layout.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _bilinear_gather(img: jax.Array, ys: jax.Array, xs: jax.Array) -> jax.Array:
    """Sample ``img [H, W, C]`` at fractional positions, zero outside.

    ``ys, xs``: any shape ``S`` of float positions. Returns ``[*S, C]``.
    """
    h, w, c = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    dy = (ys - y0).astype(img.dtype)
    dx = (xs - x0).astype(img.dtype)
    y0i = y0.astype(jnp.int32)
    x0i = x0.astype(jnp.int32)

    flat = img.reshape(h * w, c)
    out = None
    for oy, ox, wgt in (
        (0, 0, (1 - dy) * (1 - dx)),
        (0, 1, (1 - dy) * dx),
        (1, 0, dy * (1 - dx)),
        (1, 1, dy * dx),
    ):
        yi = y0i + oy
        xi = x0i + ox
        inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        idx = jnp.clip(yi, 0, h - 1) * w + jnp.clip(xi, 0, w - 1)
        v = jnp.take(flat, idx.reshape(-1), axis=0).reshape(*ys.shape, c)
        v = v * jnp.where(inb, wgt, 0.0)[..., None]
        out = v if out is None else out + v
    return out


def deform_conv2d(
    x: jax.Array,
    offsets: jax.Array,
    mask: jax.Array,
    weight: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    stride: int = 1,
    padding: int = 1,
    dilation: int = 1,
) -> jax.Array:
    """Modulated deformable conv (DCNv2 forward, reference ``dcn_v2_cuda.cu:20-95``).

    Args:
      x: ``[B, H, W, Cin]`` input features.
      offsets: ``[B, Ho, Wo, dg, K, 2]`` learned (dy, dx) per output pixel,
        deformable group and kernel tap (K = kh*kw, row-major taps).
      mask: ``[B, Ho, Wo, dg, K]`` modulation (already sigmoid'd).
      weight: ``[kh, kw, Cin, Cout]`` (HWIO).
      bias: ``[Cout]`` or None.

    Returns ``[B, Ho, Wo, Cout]``.
    """
    b, h, w, cin = x.shape
    kh, kw, wcin, cout = weight.shape
    assert wcin == cin, f"weight Cin {wcin} != input Cin {cin}"
    _, ho, wo, dg, k, _ = offsets.shape
    assert k == kh * kw
    assert cin % dg == 0, f"Cin {cin} not divisible by deformable_groups {dg}"
    cg = cin // dg

    # Base sampling grid: output pixel -> top-left input position + tap offset.
    oy = jnp.arange(ho) * stride - padding
    ox = jnp.arange(wo) * stride - padding
    ky, kx = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
    tap_y = (ky * dilation).reshape(-1).astype(jnp.float32)  # [K]
    tap_x = (kx * dilation).reshape(-1).astype(jnp.float32)

    # [Ho, Wo, 1, K] base + [B, Ho, Wo, dg, K] learned offsets
    base_y = oy[:, None, None, None].astype(jnp.float32) + tap_y[None, None, None, :]
    base_x = ox[None, :, None, None].astype(jnp.float32) + tap_x[None, None, None, :]
    ys = base_y[None] + offsets[..., 0]
    xs = base_x[None] + offsets[..., 1]

    # Gather per deformable group: x regrouped [B, dg, H, W, Cg].
    xg = x.reshape(b, h, w, dg, cg)
    xg = jnp.moveaxis(xg, 3, 1)
    # positions per group: [B, dg, Ho, Wo, K]
    ys_g = jnp.moveaxis(ys, 3, 1)
    xs_g = jnp.moveaxis(xs, 3, 1)
    sample = jax.vmap(jax.vmap(_bilinear_gather))  # over B, dg
    cols = sample(xg, ys_g, xs_g)  # [B, dg, Ho, Wo, K, Cg]
    cols = cols * jnp.moveaxis(mask, 3, 1)[..., None]

    # Contract with weight: [kh*kw, dg, Cg, Cout]. The contraction is the
    # one MXU-bound op in this composite — at narrow operand widths it must
    # accumulate in f32 (JX001, docs/ANALYSIS.md "low-precision
    # accumulation"), then round back to the operand width so the layer's
    # output dtype matches its input dtype either way.
    wk = weight.reshape(kh * kw, dg, cg, cout)
    out = jnp.einsum(
        "bgijkc,kgco->bijo", cols, wk,
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    if bias is not None:
        out = out + bias
    return out


# ``'auto'`` dispatch decisions observed during tracing, keyed
# "direction:HxW" -> impl (direction in {'train', 'fwd'}). A fwd and a
# train call at the same map size are DIFFERENT decisions with different
# gates, so they must never overwrite each other (the pre-PR-7 "HxW" key
# did exactly that). One entry per (direction, map size) per process;
# read via dispatch_log().
_DISPATCH_LOG: dict = {}

DCN_DIRECTIONS = ("train", "fwd")


def dispatch_log() -> dict:
    """Copy of the ``'auto'`` dispatch decisions traced so far (bench and
    serving evidence: which impl each DCN call site in a compiled program
    resolved to, per direction). Keys are ``"train:HxW"`` / ``"fwd:HxW"``
    strings so the log serializes straight into JSONL artifacts."""
    return dict(_DISPATCH_LOG)


def _dispatch_key(direction: str, h: int, w: int) -> str:
    return f"{direction}:{h}x{w}"


def resolve_dcn_impl(h: int, w: int, direction: str = "train") -> str:
    """The impl ``'auto'`` dispatch chooses for an ``h x w`` input map in
    the given direction (``'train'`` = forward + VJP under grad,
    ``'fwd'`` = inference/serving forward only).

    One-hot-matmul gather work scales with the map size: the fused
    kernels win at bottleneck-sized maps and lose to XLA's gather beyond
    ~4096 pixels. On top of the size rule each direction has its OWN
    one-time real-Mosaic self-test — the train direction gates on
    :func:`esr_tpu.ops.dcn_pallas.pallas_compiles` (fwd+VJP kernel pair,
    measured 3.17x on r4) and the fwd direction on
    :func:`esr_tpu.ops.dcn_pallas.pallas_fwd_compiles` (the DCNv4-style
    fused forward) — so the gates open independently per direction. A
    single shared gate would have shipped the r4 forward regression
    (``fwd_speedup`` 0.961) to the serving tier the moment train parity
    passed.
    """
    assert direction in DCN_DIRECTIONS, direction
    if h * w <= 4096:
        from esr_tpu.ops.dcn_pallas import (
            on_tpu_backend,
            pallas_compiles,
            pallas_fwd_compiles,
        )

        if on_tpu_backend():
            gate = (
                pallas_fwd_compiles if direction == "fwd" else pallas_compiles
            )
            if gate():
                return "pallas"
    return "jnp"


def deform_conv2d_auto(
    x: jax.Array,
    offsets: jax.Array,
    mask: jax.Array,
    weight: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    stride: int = 1,
    padding: int = 1,
    dilation: int = 1,
    impl: str = "auto",
    direction: str = "train",
    sparse: bool = False,
    activity: Optional[jax.Array] = None,
    tile_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Dispatch between the jnp formulation and the fused Pallas kernels.

    ``impl``: ``'auto'`` uses Pallas on TPU backends (faster AND more
    accurate — the jnp einsum pays the MXU's default bf16 rounding) and the
    jnp path elsewhere (Pallas interpret mode is for tests, not speed);
    ``'pallas'`` / ``'jnp'`` force a path.

    ``direction``: which Pallas kernel ``'pallas'`` means and which gate
    ``'auto'`` consults. ``'train'`` (default — grad-carrying call sites)
    routes :func:`esr_tpu.ops.dcn_pallas.deform_conv2d_pallas` (one-hot
    forward + fused VJP, gated by ``pallas_compiles``); ``'fwd'``
    (inference/serving — the direction the streaming engine and serving
    tier dispatch millions of times) routes the DCNv4-style fused forward
    :func:`esr_tpu.ops.dcn_pallas.deform_conv2d_pallas_fwd`, gated by
    ``pallas_fwd_compiles``. Either way ``'auto'`` can never silently
    depend on a kernel the resident compiler rejects, and the traced
    decision is logged under ``(direction, HxW)``.

    Activity-sparse compute (docs/PERF.md, ISSUE 12): ``sparse=True``
    derives the provably-invisible per-image predication mask
    (:func:`~esr_tpu.ops.dcn_pallas.dcn_image_activity` — an all-zero
    input image's output is zero for ANY offsets) and predicates the
    Pallas kernels on it; an all-zero tile block skips its gather+MXU
    loop entirely. ``activity`` (optional ``[B]``, e.g. the data plane's
    rasterization-time sidecar) is combined CONSERVATIVELY — a block is
    skipped only when BOTH the input-derived mask and the caller's
    activity call it idle, so a wrong caller annotation can only reduce
    skipping, never change numerics. ``tile_mask`` passes an explicit
    ``[B]``/``[B, n_tiles]`` bitmap through verbatim (expert callers with
    per-tile evidence own its correctness). The jnp path ignores all
    three (dense by definition), so predication rides ONLY behind the
    per-direction Mosaic gates that ``'auto'`` already consults.
    """
    assert direction in DCN_DIRECTIONS, direction
    if impl == "auto":
        impl = resolve_dcn_impl(x.shape[1], x.shape[2], direction)
        # Traced once per compile; the log is what bench.py's on-chip
        # artifact reports as step-level proof of which impl actually ran
        # (VERDICT r4: the only real-TPU capture silently dispatched jnp),
        # and what test_serve_smoke pins as the serving path's forward
        # decision.
        _DISPATCH_LOG[_dispatch_key(direction, x.shape[1], x.shape[2])] = impl
    if impl == "pallas":
        tm = tile_mask
        if tm is None and sparse:
            from esr_tpu.ops.dcn_pallas import dcn_image_activity

            tm = dcn_image_activity(x)
            if activity is not None:
                # conservative OR of the two activity views: skip only
                # when both say idle — the derived mask alone already
                # implies the input is zero, so adding caller activity
                # can only KEEP tiles, never skip a live one
                tm = jnp.maximum(
                    tm, (activity.reshape(-1) > 0).astype(jnp.float32)
                )
        if direction == "fwd":
            from esr_tpu.ops.dcn_pallas import deform_conv2d_pallas_fwd

            return deform_conv2d_pallas_fwd(
                x, offsets, mask, weight, bias, stride, padding, dilation,
                tile_mask=tm,
            )
        from esr_tpu.ops.dcn_pallas import deform_conv2d_pallas

        return deform_conv2d_pallas(
            x, offsets, mask, weight, bias, stride, padding, dilation,
            tile_mask=tm,
        )
    if impl == "jnp":
        return deform_conv2d(
            x, offsets, mask, weight, bias,
            stride=stride, padding=padding, dilation=dilation,
        )
    raise ValueError(f"unknown DCN impl {impl!r}")


def dcn_offsets_from_conv(
    raw: jax.Array, deformable_groups: int, k: int
) -> Tuple[jax.Array, jax.Array]:
    """Split the offset/mask conv output into (offsets, mask).

    ``raw``: ``[B, Ho, Wo, dg*3*K]`` from the zero-initialized offset conv
    (reference ``dcn_v2.py:214-227``): first third dy, second third dx, last
    third mask logits (sigmoid applied here).
    """
    b, ho, wo, ch = raw.shape
    dg = deformable_groups
    assert ch == dg * 3 * k
    o1, o2, m = jnp.split(raw, 3, axis=-1)
    dy = o1.reshape(b, ho, wo, dg, k)
    dx = o2.reshape(b, ho, wo, dg, k)
    offsets = jnp.stack([dy, dx], axis=-1)
    mask = jax.nn.sigmoid(m.reshape(b, ho, wo, dg, k))
    return offsets, mask
