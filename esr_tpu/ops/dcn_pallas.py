"""Pallas TPU kernel for modulated deformable convolution (DCNv2).

The fused fast path promised by SURVEY.md §7.1-3 phase B, replacing the
reference's CUDA im2col + GEMM pair
(``/root/reference/models/DCNv2/src/cuda/dcn_v2_im2col_cuda.cu:125+``,
``dcn_v2_cuda.cu:78-92``) — and the HBM round-trip of the jnp fallback
(``esr_tpu.ops.dcn.deform_conv2d`` materializes the ``[B, dg, Ho, Wo, K, Cg]``
column tensor in HBM; this kernel never does).

TPU-native formulation
----------------------
A CUDA-style per-thread scalar gather does not map to the TPU's vector units,
so the bilinear gather is recast as **one-hot matrix multiplication** on the
MXU, operating entirely in VMEM:

- host-side (XLA-fused elementwise): sampling positions = base grid + learned
  offsets; decomposed into 4 integer corner indices (flattened, clipped) and
  4 bilinear corner weights, pre-multiplied by the sigmoid modulation mask and
  zeroed outside the image (the ``dmcn_im2col_bilinear_cuda`` boundary rule);
- kernel, per batch image: for each deformable group ``g`` and kernel tap
  ``k``, build the weighted selection matrix
  ``S[hw, o] = Σ_corners (hw == idx_c[o]) · w_c[o]`` with vector compares
  against an iota (no scatter), then two MXU contractions
  ``colsᵀ = imgᵀ_g · S`` and ``acc += Wᵀ_{g,k} · colsᵀ``;
- matmuls run at ``Precision.HIGHEST``: the MXU's default bf16 rounding is a
  *gather corruption* here (values, not just precision, change) — verified
  exact against ``jnp.take`` at f32.

Everything lives in VMEM for one batch image (feature maps at the ESR
bottleneck are tiny: ``H/8 × W/8 × 8·basech``), so the only HBM traffic is
the input read and output write.

The backward pass is fused the same way (``_dcn_bwd_kernel``): the S
matrices are rebuilt in VMEM and the three cotangents come out of transposed
MXU contractions —

- ``grad_cols = Wᵀ_{g,k} · gᵀ`` then ``gxᵀ_g += grad_cols · Sᵀ`` (the
  reference's atomicAdd col2im scatter, ``dcn_v2_im2col_cuda.cu:56-123``,
  as a matmul);
- ``gw_{g,k} += (imgᵀ_g · S) · gᵀ`` (im2col column re-use without ever
  writing columns to HBM);
- per-corner weight cotangents ``gwgt_c[o] = Σ_hw 1[hw = idx_c[o]] ·
  (imgᵀ_gᵀ · grad_cols)[hw, o]`` — the same one-hot trick reduced over
  rows — which the host turns into offset/mask gradients by VJP through
  the (elementwise, XLA-fused) corner-weight computation.

``dcn_backward_impl('jnp')`` switches back to XLA autodiff of the jnp
formulation (used by tests to pin the fused gradients bit-close, and by the
bench for A/B).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from esr_tpu.ops import dcn as _dcn_jnp


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _tiling(hw: int, no: int) -> Tuple[int, int, int, int]:
    """``(hw_pad, no_tile, no_pad, n_tiles)`` shared by forward/backward.

    Output-pixel tiling bounds the S matrix (and iota) to
    ``[hw_pad, no_tile]`` f32 in VMEM; shrink the tile as the image grows.
    """
    hw_pad = _round_up(hw, 128)
    if hw_pad <= 1024:
        cap = 512
    elif hw_pad <= 4096:
        cap = 256
    else:
        cap = 128
    no_tile = min(cap, _round_up(no, 128))
    no_pad = _round_up(no, no_tile)
    return hw_pad, no_tile, no_pad, no_pad // no_tile


def _corner_pairs(
    offsets: jax.Array,
    mask: jax.Array,
    h: int,
    w: int,
    stride: int,
    padding: int,
    dilation: int,
    kh: int,
    kw: int,
) -> Tuple[jax.Array, jax.Array]:
    """Sampling positions -> 4 (index, weight) corner pairs per tap, in the
    natural ``[B, Ho, Wo, dg, K, 4]`` layout. Differentiable in
    ``(offsets, mask)`` — the fused backward takes the VJP of the weight
    output to turn corner-weight cotangents into offset/mask gradients."""
    ho, wo = offsets.shape[1], offsets.shape[2]

    oy = jnp.arange(ho) * stride - padding
    ox = jnp.arange(wo) * stride - padding
    ky, kx = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
    tap_y = (ky * dilation).reshape(-1).astype(jnp.float32)
    tap_x = (kx * dilation).reshape(-1).astype(jnp.float32)

    base_y = oy[:, None, None, None].astype(jnp.float32) + tap_y[None, None, None, :]
    base_x = ox[None, :, None, None].astype(jnp.float32) + tap_x[None, None, None, :]
    ys = base_y[None] + offsets[..., 0]  # [B, Ho, Wo, dg, K]
    xs = base_x[None] + offsets[..., 1]

    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    dy = ys - y0
    dx = xs - x0

    idxs, wgts = [], []
    for cy, cx, cw in (
        (0, 0, (1 - dy) * (1 - dx)),
        (0, 1, (1 - dy) * dx),
        (1, 0, dy * (1 - dx)),
        (1, 1, dy * dx),
    ):
        yi = y0.astype(jnp.int32) + cy
        xi = x0.astype(jnp.int32) + cx
        inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        flat = jnp.clip(yi, 0, h - 1) * w + jnp.clip(xi, 0, w - 1)
        idxs.append(jnp.where(inb, flat, 0))
        wgts.append(jnp.where(inb, cw, 0.0) * mask)

    return jnp.stack(idxs, axis=-1), jnp.stack(wgts, axis=-1)


def _corner_decomposition(
    offsets: jax.Array,
    mask: jax.Array,
    h: int,
    w: int,
    stride: int,
    padding: int,
    dilation: int,
    kh: int,
    kw: int,
    hw_pad: int,
    no_pad: int,
) -> Tuple[jax.Array, jax.Array]:
    """Corner pairs in kernel layout: ``idx [B, dg, 4, K, No_pad] int32``
    and ``wgt [B, dg, 4, K, No_pad] f32`` (mask-premultiplied, zero when
    the corner falls outside the image or in the No padding)."""
    b, ho, wo, dg, k, _ = offsets.shape
    no = ho * wo
    idx, wgt = _corner_pairs(
        offsets, mask, h, w, stride, padding, dilation, kh, kw
    )
    # [B, Ho, Wo, dg, K, 4] -> [B, dg, 4, K, No]
    idx = idx.reshape(b, no, dg, k, 4).transpose(0, 2, 4, 3, 1)
    wgt = wgt.reshape(b, no, dg, k, 4).transpose(0, 2, 4, 3, 1)

    idx = jnp.pad(idx, ((0, 0), (0, 0), (0, 0), (0, 0), (0, no_pad - no)))
    wgt = jnp.pad(wgt, ((0, 0), (0, 0), (0, 0), (0, 0), (0, no_pad - no)))
    return idx.astype(jnp.int32), wgt.astype(jnp.float32)


def _fwd_tiling(h: int, w: int, no: int) -> Tuple[int, int, int, int, int]:
    """``(h_pad, w_pad, no_tile, no_pad, n_tiles)`` for the DCNv4-style
    fused forward kernel.

    The 2006.05238 line-buffer scheme: the x-gather contracts along rows
    (one W-wide "line" per input row held in VMEM), so only ``w`` pays the
    128-lane padding and only ``h`` the 8-sublane padding — the one-hot
    selection matrices shrink from ``[H*W, No]`` to ``[W, No] + [H, No]``.
    The output-tile cap DELEGATES to :func:`_tiling`'s VMEM budget on the
    padded pixel count (one ladder, two kernels — a recalibration there
    must not leave this kernel on a stale budget).
    """
    h_pad = _round_up(h, 8)
    w_pad = _round_up(w, 128)
    # h_pad*w_pad is already a 128-multiple, so _tiling's hw_pad == it
    _, no_tile, no_pad, n_tiles = _tiling(h_pad * w_pad, no)
    return h_pad, w_pad, no_tile, no_pad, n_tiles


def _separable_corner_pairs(
    offsets: jax.Array,
    mask: jax.Array,
    h: int,
    w: int,
    stride: int,
    padding: int,
    dilation: int,
    kh: int,
    kw: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Sampling positions -> separable axis factors ``(yi, wy, xi, wx)``,
    each ``[B, Ho, Wo, dg, K, 2]`` (2 = the two corners per axis).

    The bilinear corner weight and the zero-outside boundary rule both
    factorize: ``w_(cy,cx) = (lerp_y·inb_y·mask) · (lerp_x·inb_x)`` — the
    modulation mask rides the y factor (applied exactly once per sample).
    This is what lets the fused forward gather with a ``[W, No]`` one-hot
    (MXU) plus an ``[H, No]`` lerp (VPU) instead of a ``[H*W, No]``
    one-hot per corner."""
    ho, wo = offsets.shape[1], offsets.shape[2]

    oy = jnp.arange(ho) * stride - padding
    ox = jnp.arange(wo) * stride - padding
    ky, kx = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
    tap_y = (ky * dilation).reshape(-1).astype(jnp.float32)
    tap_x = (kx * dilation).reshape(-1).astype(jnp.float32)

    base_y = oy[:, None, None, None].astype(jnp.float32) + tap_y[None, None, None, :]
    base_x = ox[None, :, None, None].astype(jnp.float32) + tap_x[None, None, None, :]
    ys = base_y[None] + offsets[..., 0]  # [B, Ho, Wo, dg, K]
    xs = base_x[None] + offsets[..., 1]

    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    dy = ys - y0
    dx = xs - x0

    yis, wys, xis, wxs = [], [], [], []
    for c, lerp in ((0, 1 - dy), (1, dy)):
        yi = y0.astype(jnp.int32) + c
        inb = (yi >= 0) & (yi < h)
        yis.append(jnp.where(inb, jnp.clip(yi, 0, h - 1), 0))
        wys.append(jnp.where(inb, lerp, 0.0) * mask)
    for c, lerp in ((0, 1 - dx), (1, dx)):
        xi = x0.astype(jnp.int32) + c
        inb = (xi >= 0) & (xi < w)
        xis.append(jnp.where(inb, jnp.clip(xi, 0, w - 1), 0))
        wxs.append(jnp.where(inb, lerp, 0.0))
    return (
        jnp.stack(yis, axis=-1),
        jnp.stack(wys, axis=-1),
        jnp.stack(xis, axis=-1),
        jnp.stack(wxs, axis=-1),
    )


def _separable_corner_decomposition(
    offsets: jax.Array,
    mask: jax.Array,
    h: int,
    w: int,
    stride: int,
    padding: int,
    dilation: int,
    kh: int,
    kw: int,
    no_pad: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Separable pairs in kernel layout: ``yi/xi [B, dg, 2, K, No_pad]``
    int32, ``wy/wx [B, dg, 2, K, No_pad]`` f32 (weights zero in the No
    padding, so padded output columns contribute nothing)."""
    b, ho, wo, dg, k, _ = offsets.shape
    no = ho * wo
    yi, wy, xi, wx = _separable_corner_pairs(
        offsets, mask, h, w, stride, padding, dilation, kh, kw
    )

    def to_kernel(arr, dtype):
        # [B, Ho, Wo, dg, K, 2] -> [B, dg, 2, K, No_pad]
        arr = arr.reshape(b, no, dg, k, 2).transpose(0, 2, 4, 3, 1)
        arr = jnp.pad(
            arr, ((0, 0), (0, 0), (0, 0), (0, 0), (0, no_pad - no))
        )
        return arr.astype(dtype)

    return (
        to_kernel(yi, jnp.int32),
        to_kernel(wy, jnp.float32),
        to_kernel(xi, jnp.int32),
        to_kernel(wx, jnp.float32),
    )


def _dcn_fwd_tile_acc(
    xg_ref, yi_ref, wy_ref, xi_ref, wx_ref, wt_ref,
    *, dg, cg, k, h_pad, w_pad, no_tile, cout,
):
    """The DCNv4-style fused-forward tile body (docstring on
    :func:`_dcn_fwd_kernel`), returning the accumulated ``[Cout,
    no_tile]`` tile — shared verbatim by the dense kernel and the
    activity-predicated variant so predication can never fork the math."""
    from jax.experimental import pallas as pl

    HIGH = jax.lax.Precision.HIGHEST
    iota_x = jax.lax.broadcasted_iota(jnp.int32, (w_pad, no_tile), 0)
    iota_y = jax.lax.broadcasted_iota(jnp.int32, (h_pad, no_tile), 0)

    def body(i, acc):
        g = i // k
        kk = i % k
        rows = xg_ref[0, pl.ds(g * cg * h_pad, cg * h_pad), :]  # [Cg*Hp, Wp]
        a = jnp.zeros((w_pad, no_tile), jnp.float32)
        for c in range(2):
            a = a + jnp.where(
                iota_x == xi_ref[0, g, c, kk, :][None, :],
                wx_ref[0, g, c, kk, :][None, :], 0.0,
            )
        # T [Cg*Hp, no_tile] = rows @ A: the x-gather as a line contraction
        t = jax.lax.dot_general(
            rows, a, (((1,), (0,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32,
        )
        bsel = jnp.zeros((h_pad, no_tile), jnp.float32)
        for c in range(2):
            bsel = bsel + jnp.where(
                iota_y == yi_ref[0, g, c, kk, :][None, :],
                wy_ref[0, g, c, kk, :][None, :], 0.0,
            )
        # V [Cg, no_tile]: y-lerp + reduce, vectorized over the group axis
        v = jnp.sum(t.reshape(cg, h_pad, no_tile) * bsel[None], axis=1)
        # acc [Cout, no_tile] += Wt[g, kk] [Cout, Cg] @ V
        return acc + jax.lax.dot_general(
            wt_ref[g, kk], v, (((1,), (0,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32,
        )

    return jax.lax.fori_loop(
        0, dg * k, body, jnp.zeros((cout, no_tile), jnp.float32)
    )


def _dcn_fwd_kernel(
    xg_ref, yi_ref, wy_ref, xi_ref, wx_ref, wt_ref, out_ref,
    *, dg, cg, k, h_pad, w_pad, no_tile, cout,
):
    """DCNv4-style fused forward: one (batch image, output tile) per
    program, ``fori_loop`` over (group, tap) pairs, ONE f32 accumulator
    tile in VMEM, no ``(dg, k, HW)`` sampled-patch matrix ever built.

    Per pair the 2006.05238 line-buffer factorization replaces the
    ``[HW, No]`` one-hot of :func:`_dcn_kernel` with:

    - ``A [Wp, No]``: x-axis one-hot (2 corners) weighted by the x-lerp —
      built with 2 vector compares over ``Wp`` rows, not 4 over ``H*W``;
    - ``T = rows·A`` where ``rows [Cg·Hp, Wp]`` is the group's image with
      H folded into the row axis — the x-gather for EVERY input line of
      EVERY group channel in one well-shaped MXU contraction (the
      channel-group axis is vectorized into M instead of looping corners);
    - ``B [Hp, No]``: y-axis lerp (mask-premultiplied) applied as an
      elementwise multiply + 8-sublane reduction over H — ``Cg·Hp·No``
      VPU work vs the old ``4·HW·No`` compare cascade;
    - ``acc += W_{g,k}·V`` into the single output accumulator.

    Sampling weights are the raw sigmoid modulation — unnormalized, per
    DCNv4 (arxiv 2401.06197): no softmax over taps anywhere.
    """
    out_ref[0] = _dcn_fwd_tile_acc(
        xg_ref, yi_ref, wy_ref, xi_ref, wx_ref, wt_ref,
        dg=dg, cg=cg, k=k, h_pad=h_pad, w_pad=w_pad,
        no_tile=no_tile, cout=cout,
    )


def _dcn_fwd_kernel_masked(
    am_ref, xg_ref, yi_ref, wy_ref, xi_ref, wx_ref, wt_ref, out_ref,
    *, dg, cg, k, h_pad, w_pad, no_tile, cout,
):
    """Activity-predicated twin of :func:`_dcn_fwd_kernel` (DCNv4's
    dynamic-sparsity reading, arxiv 2401.06197; region-skipping per arxiv
    2006.05238): ``am_ref`` is the scalar-prefetched ``[B, n_tiles]``
    tile-activity bitmap in SMEM, and an inactive (batch image, output
    tile) program skips the whole gather + MXU contraction loop and
    zero-fills its accumulator tile instead — numerically invisible by
    the mask's contract (every value the tile's gathers could touch is
    zero, so the dense result IS the zero tile; judged by the same
    ``dcn_*_parity_ok`` ladders as the dense kernels)."""
    from jax.experimental import pallas as pl

    active = am_ref[pl.program_id(0), pl.program_id(1)] > 0

    @pl.when(active)
    def _compute():
        out_ref[0] = _dcn_fwd_tile_acc(
            xg_ref, yi_ref, wy_ref, xi_ref, wx_ref, wt_ref,
            dg=dg, cg=cg, k=k, h_pad=h_pad, w_pad=w_pad,
            no_tile=no_tile, cout=cout,
        )

    @pl.when(jnp.logical_not(active))
    def _skip():
        out_ref[0] = jnp.zeros((cout, no_tile), jnp.float32)


def _tile_mask_grid(tile_mask: jax.Array, b: int, n_tiles: int) -> jax.Array:
    """Normalize a caller activity mask onto a kernel's ``(b, n_tiles)``
    grid: ``[B]`` per-image activity broadcasts over every output tile
    (the idle-window case — an all-zero input image zeroes ALL its
    tiles); ``[B, n_tiles]`` passes through for callers with per-tile
    evidence. Returns the int32 bitmap the kernels branch on."""
    am = jnp.asarray(tile_mask)
    if am.ndim == 1:
        am = jnp.broadcast_to(am[:, None], (b, n_tiles))
    if am.shape != (b, n_tiles):
        raise ValueError(
            f"tile_mask shape {am.shape} does not match the kernel grid "
            f"({b}, {n_tiles}); pass [B] per-image activity or the exact "
            f"[B, n_tiles] per-output-tile bitmap"
        )
    return (am > 0).astype(jnp.int32)


def dcn_image_activity(x: jax.Array) -> jax.Array:
    """``[B]`` f32 per-image activity: 1.0 where ANY input value is
    nonzero. This is the provably-invisible predication mask — an
    all-zero input image's deformable-conv output (pre-bias) is zero for
    EVERY possible offset/modulation, so skipping all its tile programs
    cannot change a single output bit. The activity-mask plane's
    ``sparse`` auto-dispatch derives it at trace time (one tiny
    reduction, XLA-fused with the staging elementwise work).

    NaN inputs count as ACTIVE: ``max(|x|) > 0`` is False for a NaN max,
    which would otherwise classify a NaN-poisoned image as idle and
    replace its (correctly NaN) dense output with clean zeros — exactly
    the kind of silent divergence masking the numerically-invisible
    contract forbids. A NaN image must flow through the dense path and
    surface loudly."""
    m = jnp.max(jnp.abs(x), axis=tuple(range(1, x.ndim)))
    return ((m > 0) | jnp.isnan(m)).astype(jnp.float32)


def _pallas_forward_fused(
    x: jax.Array,
    offsets: jax.Array,
    mask: jax.Array,
    weight: jax.Array,
    stride: int,
    padding: int,
    dilation: int,
    interpret: bool,
    tile_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Host-side staging for :func:`_dcn_fwd_kernel` (the DCNv4-style
    forward). Layout: the image is pre-transposed to ``[B, C·Hp, Wp]`` so
    each group's ``[Cg·Hp, Wp]`` line block is one contiguous row slice.
    ``tile_mask`` (optional, [B] or [B, n_tiles]) routes the
    activity-predicated kernel; ``None`` builds the EXACT dense program
    shipped before the activity plane existed."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, w, cin = x.shape
    kh, kw, wcin, cout = weight.shape
    _, ho, wo, dg, k, _ = offsets.shape
    assert wcin == cin and k == kh * kw and cin % dg == 0
    # f32 operands throughout, same rationale as _pallas_forward: the
    # one-hot/lerp selection must not round in bf16 (gather corruption).
    x = x.astype(jnp.float32)
    offsets = offsets.astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    weight = weight.astype(jnp.float32)
    cg = cin // dg
    no = ho * wo
    h_pad, w_pad, no_tile, no_pad, n_tiles = _fwd_tiling(h, w, no)

    yi, wy, xi, wx = _separable_corner_decomposition(
        offsets, mask, h, w, stride, padding, dilation, kh, kw, no_pad
    )

    # x [B, H, W, C] -> [B, C*Hp, Wp] (group-major rows: channel c of
    # group g lands at row (g*Cg + c_g)*Hp + y)
    xg = x.transpose(0, 3, 1, 2)
    xg = jnp.pad(xg, ((0, 0), (0, 0), (0, h_pad - h), (0, w_pad - w)))
    xg = xg.reshape(b, cin * h_pad, w_pad)
    # weight HWIO -> [dg, K, Cout, Cg]
    wt = weight.reshape(k, dg, cg, cout).transpose(1, 0, 3, 2)

    pair_spec = pl.BlockSpec(
        (1, dg, 2, k, no_tile), lambda i, t: (i, 0, 0, 0, t),
        memory_space=pltpu.VMEM,
    )
    in_specs = [
        pl.BlockSpec((1, cin * h_pad, w_pad), lambda i, t: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pair_spec, pair_spec, pair_spec, pair_spec,
        pl.BlockSpec((dg, k, cout, cg), lambda i, t: (0, 0, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [xg, yi, wy, xi, wx, wt]
    if tile_mask is None:
        kernel = functools.partial(
            _dcn_fwd_kernel,
            dg=dg, cg=cg, k=k, h_pad=h_pad, w_pad=w_pad,
            no_tile=no_tile, cout=cout,
        )
    else:
        kernel = functools.partial(
            _dcn_fwd_kernel_masked,
            dg=dg, cg=cg, k=k, h_pad=h_pad, w_pad=w_pad,
            no_tile=no_tile, cout=cout,
        )
        # the whole bitmap rides SMEM (scalar memory): the per-program
        # branch scalar is prefetched, never a VMEM tile load
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
        operands = [_tile_mask_grid(tile_mask, b, n_tiles)] + operands
    out_t = pl.pallas_call(
        kernel,
        grid=(b, n_tiles),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, cout, no_tile), lambda i, t: (i, 0, t),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b, cout, no_pad), jnp.float32),
        interpret=interpret,
    )(*operands)

    return out_t[:, :, :no].transpose(0, 2, 1).reshape(b, ho, wo, cout)


def _dcn_tile_acc(
    xt_ref, idx_ref, wgt_ref, wt_ref, *, dg, cg, k, hw_pad, no_tile, cout
):
    """The one-hot-gather tile body of :func:`_dcn_kernel`, returning the
    accumulated ``[Cout, no_tile]`` tile — shared verbatim by the dense
    kernel and the activity-predicated variant so predication can never
    fork the math."""
    from jax.experimental import pallas as pl

    HIGH = jax.lax.Precision.HIGHEST
    iota = jax.lax.broadcasted_iota(jnp.int32, (hw_pad, no_tile), 0)

    def body(i, acc):
        g = i // k
        kk = i % k
        img_g = xt_ref[0, pl.ds(g * cg, cg), :]  # [Cg, HWp]
        s = jnp.zeros((hw_pad, no_tile), jnp.float32)
        for c in range(4):
            iv = idx_ref[0, g, c, kk, :]  # [no_tile] lane vector
            wv = wgt_ref[0, g, c, kk, :]
            s = s + jnp.where(iota == iv[None, :], wv[None, :], 0.0)
        # colsT [Cg, no_tile] = imgT_g [Cg, HWp] @ S [HWp, no_tile]
        cols = jax.lax.dot_general(
            img_g, s, (((1,), (0,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32,
        )
        # acc [Cout, no_tile] += Wt[g, kk] [Cout, Cg] @ colsT
        return acc + jax.lax.dot_general(
            wt_ref[g, kk], cols, (((1,), (0,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32,
        )

    return jax.lax.fori_loop(
        0, dg * k, body, jnp.zeros((cout, no_tile), jnp.float32)
    )


def _dcn_kernel(xt_ref, idx_ref, wgt_ref, wt_ref, out_ref, *, dg, cg, k, hw_pad, no_tile, cout):
    """One (batch image, output tile) per program; ``fori_loop`` over the
    flattened (group, tap) pairs keeps VMEM to one S matrix at a time and
    writes the f32 accumulator exactly once."""
    out_ref[0] = _dcn_tile_acc(
        xt_ref, idx_ref, wgt_ref, wt_ref,
        dg=dg, cg=cg, k=k, hw_pad=hw_pad, no_tile=no_tile, cout=cout,
    )


def _dcn_kernel_masked(
    am_ref, xt_ref, idx_ref, wgt_ref, wt_ref, out_ref,
    *, dg, cg, k, hw_pad, no_tile, cout,
):
    """Activity-predicated twin of :func:`_dcn_kernel` — the
    train-direction half of the block-predication plane (docstring on
    :func:`_dcn_fwd_kernel_masked`): inactive (image, tile) programs skip
    the ``dg*k`` gather+contraction loop and zero-fill the accumulator.
    Predication covers the PRIMAL forward only — the backward stays
    dense, because ``gx`` of a zero input block is NOT zero (it is the
    col2im transport of the upstream cotangent into that block), so
    skipping it there would not be numerically invisible."""
    from jax.experimental import pallas as pl

    active = am_ref[pl.program_id(0), pl.program_id(1)] > 0

    @pl.when(active)
    def _compute():
        out_ref[0] = _dcn_tile_acc(
            xt_ref, idx_ref, wgt_ref, wt_ref,
            dg=dg, cg=cg, k=k, hw_pad=hw_pad, no_tile=no_tile, cout=cout,
        )

    @pl.when(jnp.logical_not(active))
    def _skip():
        out_ref[0] = jnp.zeros((cout, no_tile), jnp.float32)


def _pallas_forward(
    x: jax.Array,
    offsets: jax.Array,
    mask: jax.Array,
    weight: jax.Array,
    stride: int,
    padding: int,
    dilation: int,
    interpret: bool,
    tile_mask: Optional[jax.Array] = None,
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, w, cin = x.shape
    kh, kw, wcin, cout = weight.shape
    _, ho, wo, dg, k, _ = offsets.shape
    assert wcin == cin and k == kh * kw and cin % dg == 0
    # The kernel is the accuracy-oriented path: all operands f32 (Mosaic
    # rejects mixed-dtype dots, and the one-hot S matmul wants f32 anyway).
    # Callers in bf16 pipelines get their dtype restored by the wrapper.
    x = x.astype(jnp.float32)
    offsets = offsets.astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    weight = weight.astype(jnp.float32)
    cg = cin // dg
    no = ho * wo
    hw_pad, no_tile, no_pad, n_tiles = _tiling(h * w, no)

    idx, wgt = _corner_decomposition(
        offsets, mask, h, w, stride, padding, dilation, kh, kw, hw_pad, no_pad
    )

    # x [B, H, W, C] -> xT [B, C, HWp]
    xt = x.reshape(b, h * w, cin).transpose(0, 2, 1)
    xt = jnp.pad(xt, ((0, 0), (0, 0), (0, hw_pad - h * w)))
    # weight HWIO -> [dg, K, Cout, Cg]
    wt = weight.reshape(k, dg, cg, cout).transpose(1, 0, 3, 2)

    in_specs = [
        pl.BlockSpec((1, cin, hw_pad), lambda i, t: (i, 0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, dg, 4, k, no_tile), lambda i, t: (i, 0, 0, 0, t), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, dg, 4, k, no_tile), lambda i, t: (i, 0, 0, 0, t), memory_space=pltpu.VMEM),
        pl.BlockSpec((dg, k, cout, cg), lambda i, t: (0, 0, 0, 0), memory_space=pltpu.VMEM),
    ]
    operands = [xt, idx, wgt, wt]
    if tile_mask is None:
        # the EXACT dense program shipped before the activity plane
        kernel = functools.partial(
            _dcn_kernel, dg=dg, cg=cg, k=k, hw_pad=hw_pad, no_tile=no_tile, cout=cout
        )
    else:
        kernel = functools.partial(
            _dcn_kernel_masked,
            dg=dg, cg=cg, k=k, hw_pad=hw_pad, no_tile=no_tile, cout=cout,
        )
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
        operands = [_tile_mask_grid(tile_mask, b, n_tiles)] + operands
    out_t = pl.pallas_call(
        kernel,
        grid=(b, n_tiles),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, cout, no_tile), lambda i, t: (i, 0, t), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b, cout, no_pad), jnp.float32),
        interpret=interpret,
    )(*operands)

    # [B, Cout, Nop] -> [B, Ho, Wo, Cout]
    return out_t[:, :, :no].transpose(0, 2, 1).reshape(b, ho, wo, cout)


def _auto_interpret() -> bool:
    return jax.default_backend() == "cpu"


def on_tpu_backend() -> bool:
    """True when the default backend is a real TPU (including the axon
    tunnel, whose backend name may differ but whose device kind is TPU)."""
    if jax.default_backend() == "tpu":
        return True
    try:
        return jax.devices()[0].device_kind.startswith("TPU")
    except Exception:  # noqa: BLE001 - no backend at all
        return False


def dcn_parity_errors(
    x, off, mask, wt, interpret: bool = False,
    matmul_precision: Optional[str] = "highest",
    tile_mask: Optional[jax.Array] = None,
) -> dict:
    """Forward + all-four-cotangent parity of the fused kernel against the
    jnp formulation at the given inputs. Used by BOTH the production
    ``pallas_compiles`` gate (tiny shape) and bench.py's ``mosaic_dcn``
    stage (flagship shape), so the comparison logic cannot drift between
    them. Pins the fused backward for the comparison (with ``'jnp'`` active
    the VJP check would be jnp-vs-jnp, vacuously true).

    ``matmul_precision``: by default both formulations are traced under
    ``jax.default_matmul_precision('highest')`` so the MXU's default-bf16
    rounding — which the two formulations apply in *different* places —
    drops out and the strict 1e-3 tolerance stays meaningful on TPU
    (ADVICE r4: a loosened on-TPU tolerance would let a ~1% kernel defect
    ship silently). ``None`` measures under production numerics instead.

    Returns ``{"fwd_max_err", "fwd_scale", "gx_rel_err", "goff_rel_err",
    "gmask_rel_err", "gw_rel_err"}`` (absolute fwd error; per-cotangent
    max-abs error over the jnp cotangent's max-abs scale).

    ``tile_mask`` (activity-sparse compute, ISSUE 12) applies block
    predication to the PALLAS side only — the jnp reference stays dense —
    so a truthful mask must leave every error inside the same ladder:
    predication is proven numerically invisible by the same criterion
    that gates the dense kernels.
    """
    import contextlib

    global _BACKWARD_IMPL
    prev_impl = _BACKWARD_IMPL
    _BACKWARD_IMPL = "pallas"
    prec_ctx = (
        jax.default_matmul_precision(matmul_precision)
        if matmul_precision else contextlib.nullcontext()
    )
    prec_ctx.__enter__()  # explicit: keeps the try/finally shape below flat
    try:
        def loss(fn):
            def f(x_, o_, m_, w_):
                return (fn(x_, o_, m_, w_) ** 2).sum()

            return f

        out = deform_conv2d_pallas(
            x, off, mask, wt, interpret=interpret, tile_mask=tile_mask
        )
        ref = _dcn_jnp.deform_conv2d(x, off, mask, wt)
        gp = jax.grad(
            loss(lambda *a: deform_conv2d_pallas(
                *a, interpret=interpret, tile_mask=tile_mask)),
            argnums=(0, 1, 2, 3),
        )(x, off, mask, wt)
        gj = jax.grad(
            loss(lambda *a: _dcn_jnp.deform_conv2d(*a)), argnums=(0, 1, 2, 3)
        )(x, off, mask, wt)
        errs = {
            "fwd_max_err": float(jnp.max(jnp.abs(out - ref))),
            "fwd_scale": float(jnp.max(jnp.abs(ref))),
        }
        for name, a, b_ in zip(("gx", "goff", "gmask", "gw"), gp, gj):
            gscale = float(jnp.max(jnp.abs(b_))) or 1.0
            errs[f"{name}_rel_err"] = float(jnp.max(jnp.abs(a - b_))) / gscale
        return errs
    finally:
        prec_ctx.__exit__(None, None, None)
        _BACKWARD_IMPL = prev_impl


def dcn_parity_ok(
    errs: dict, tol: float | None = None,
    matmul_precision: Optional[str] = "highest",
) -> bool:
    """The pass criterion shared by the gate and the bench stage.

    Every comparison is RELATIVE: the forward tolerance is normalized by
    the output scale (``fwd_scale``, floored at 1 so near-zero outputs
    fall back to an absolute criterion instead of dividing by noise), and
    the cotangent errors arrive already scale-normalized from
    :func:`dcn_parity_errors`. What the r4 on-chip capture exposed was a
    TOLERANCE miscalibration, not a missing normalization (the fwd check
    was scale-normalized then too): the capture measured ``fwd_max_err``
    4.5e-3 at ``fwd_scale`` ~2.07 (2.2e-3 *relative*) and cotangents at
    1.4-3.1e-3 — the f32-accumulation envelope of this kernel pair on
    real hardware — against the 1e-3 bound calibrated for f32-EXACT
    backends, so the flagship record shows ``dcn_pallas_mosaic_ok:
    false`` on a healthy kernel and ``auto`` dispatch never opened.

    Tolerance calibration by mode:

    - pinned ``matmul_precision='highest'`` off-TPU: 1e-3 — both
      formulations are f32-exact there (CPU interpret / the defect
      screen), so this stays the strict, defect-catching bound;
    - pinned, ON TPU: 5e-3 — the r4 capture measured 1.4-3.1e-3 relative
      disagreement at the flagship shape *under the pin* (accumulation
      *order* still differs between the one-hot contractions and the
      im2col einsum, and 'highest' is multi-pass bf16 on this hardware,
      not literal f32); 5e-3 clears that measured envelope with margin
      while real indexing/weighting defects sit at O(1), ~200x away.
      ADVICE r4's concern (a ~1% defect shipping inside a loosened
      allowance) is held: 5e-3 is still below 1%, and the CPU-interpret
      defect screen in :func:`pallas_compiles` keeps the f32-exact 1e-3
      bound on the same kernel trace;
    - production numerics (``matmul_precision=None``) on TPU: 2e-2 — the
      MXU multiplies f32 operands in bf16 and the two formulations round
      in different places (measured 2-4e-3 on v5 lite, r4 bench
      ``mosaic_dcn``); ~5x headroom, still failing hard on real bugs.
    """
    return dcn_fwd_parity_ok(errs, tol, matmul_precision) and all(
        errs[f"{n}_rel_err"] <= _parity_tol(tol, matmul_precision)
        for n in ("gx", "goff", "gmask", "gw")
    )


def _parity_tol(tol: float | None, matmul_precision: Optional[str]) -> float:
    """The calibrated tolerance ladder documented on :func:`dcn_parity_ok`,
    shared verbatim by the forward-only criterion."""
    if tol is not None:
        return tol
    if matmul_precision:
        return 5e-3 if on_tpu_backend() else 1e-3
    return 2e-2 if on_tpu_backend() else 1e-3


def dcn_fwd_parity_ok(
    errs: dict, tol: float | None = None,
    matmul_precision: Optional[str] = "highest",
) -> bool:
    """The forward half of :func:`dcn_parity_ok`'s criterion — the SAME
    scale-normalized comparison (``fwd_max_err`` over ``fwd_scale`` floored
    at 1) at the SAME calibrated tolerances — applied alone. This is the
    pass criterion for the DCNv4-style fused forward kernel, whose gate
    (:func:`pallas_fwd_compiles`) has no cotangents to check: its backward
    is the already-gated :func:`_pallas_backward`."""
    tol = _parity_tol(tol, matmul_precision)
    return errs["fwd_max_err"] <= tol * max(errs["fwd_scale"], 1.0)


def dcn_fwd_parity_errors(
    x, off, mask, wt, interpret: bool = False,
    matmul_precision: Optional[str] = "highest",
    tile_mask: Optional[jax.Array] = None,
) -> dict:
    """Forward-only parity of the DCNv4-style fused kernel
    (:func:`deform_conv2d_pallas_fwd`) against the jnp formulation —
    the same measurement :func:`dcn_parity_errors` makes for the
    train-direction kernel, restricted to the forward fields. Used by
    BOTH the production forward-dispatch gate (tiny shape) and bench.py's
    ``dcn_fwd_ab`` stage (flagship shape). ``tile_mask`` predicates the
    pallas side only (jnp stays dense), so activity masking is judged by
    the same scale-normalized ladder as the dense kernel."""
    import contextlib

    prec_ctx = (
        jax.default_matmul_precision(matmul_precision)
        if matmul_precision else contextlib.nullcontext()
    )
    with prec_ctx:
        out = deform_conv2d_pallas_fwd(
            x, off, mask, wt, interpret=interpret, tile_mask=tile_mask
        )
        ref = _dcn_jnp.deform_conv2d(x, off, mask, wt)
    return {
        "fwd_max_err": float(jnp.max(jnp.abs(out - ref))),
        "fwd_scale": float(jnp.max(jnp.abs(ref))),
    }


# How the last pallas_compiles() gate decision was reached — surfaced by
# bench.py's mosaic_dcn stage so the on-chip artifact records whether the
# strict pinned-precision tolerance held or the production-numerics
# fallback was needed. None until the gate has run. _GATE_FALLBACK is the
# STRUCTURED flag consumers branch on (the mode string is display-only).
_GATE_MODE: Optional[str] = None
_GATE_FALLBACK: bool = False


def gate_mode() -> Optional[str]:
    """Which parity mode the production dispatch gate passed (or None).
    Human-readable; branch on :func:`gate_used_fallback` instead."""
    return _GATE_MODE


def gate_used_fallback() -> bool:
    """True when the gate passed via the production-numerics fallback
    (precision pin ignored by the kernel) rather than the strict
    pinned-precision check."""
    return _GATE_FALLBACK


@functools.lru_cache(maxsize=None)
def pallas_compiles() -> bool:
    """Has the fused kernel passed a REAL Mosaic compile+exec this process?

    Compiles forward + full VJP with ``interpret=False`` at a tiny shape and
    cross-checks BOTH the output and all four cotangents against the jnp
    formulation (a backward that compiles-but-miscomputes must fail the gate
    too). The check runs under pinned ``'highest'`` matmul precision at the
    scale-normalized strict tolerance (:func:`dcn_parity_ok`: 5e-3 on TPU,
    calibrated to the r4-measured 1.4-3.1e-3 f32-accumulation-scale
    envelope at the flagship shape; ADVICE r4's concern — a ~1% kernel
    defect must fail, not hide inside an MXU-rounding allowance — is held
    by the margin to O(1) defect errors plus the f32-exact CPU defect
    screen below). The production-numerics
    fallback (backend-aware 2e-2) is reachable ONLY when (a) the kernel's
    outputs+cotangents are bit-identical across precision modes — the pin
    never reached the kernel's dots, so the pinned comparison proved
    nothing about it — AND (b) the backend-independent defect screen
    passes: the same kernel trace in interpret mode on the CPU device
    (f32-exact, no MXU) agrees with the jnp formulation at 1e-3, which a
    deterministic indexing/weighting bug cannot. A strict-tolerance
    failure with pinning honored fails the gate outright.
    :func:`gate_mode` records which branch decided;
    :func:`gate_used_fallback` is the structured flag.
    Memoized; returns False off-TPU — interpreter mode proves nothing about
    Mosaic, and the kernel's one-hot-MXU formulation is TPU-designed, not a
    GPU/Triton candidate. ``deform_conv2d_auto`` gates its Pallas dispatch
    on this, so the production default can never route through a kernel the
    resident compiler rejects — the concern VERDICT r3 raised about
    accumulating output blocks / ``pl.ds`` group slicing / ``@pl.when``
    init never having met Mosaic.
    """
    global _GATE_MODE, _GATE_FALLBACK
    _GATE_FALLBACK = False
    if not on_tpu_backend():
        _GATE_MODE = "off-tpu (gate closed)"
        return False
    import contextlib
    import warnings

    import numpy as np

    try:
        rng = np.random.default_rng(0)
        b, h, w, c, dg = 1, 4, 6, 16, 2
        x = jnp.asarray(rng.standard_normal((b, h, w, c)), jnp.float32)
        off = jnp.asarray(
            rng.standard_normal((b, h, w, dg, 9, 2)), jnp.float32
        )
        mask = jax.nn.sigmoid(
            jnp.asarray(rng.standard_normal((b, h, w, dg, 9)), jnp.float32)
        )
        wt = jnp.asarray(
            rng.standard_normal((3, 3, c, c)) * 0.1, jnp.float32
        )

        errs = dcn_parity_errors(x, off, mask, wt, interpret=False)
        if dcn_parity_ok(errs):
            # on-TPU strict tolerance is the scale-normalized 5e-3 (r4
            # f32-accumulation envelope); off-TPU never reaches this branch
            _GATE_MODE = "matmul_precision=highest tol=5e-3 (scale-normalized)"
            return True

        # Strict check failed. Fallback is legitimate only if the backend
        # ignored the precision pin for the kernel: compare each path
        # against ITSELF across precision modes — forward AND all four
        # cotangents, so a backward-only defect cannot hide behind a
        # forward-only "pin ignored" verdict. jnp sensitive + kernel
        # insensitive => the pinned comparison mixed f32 against bf16
        # numerics by construction; anything else => treat as a defect.
        def _probe(pin):
            global _BACKWARD_IMPL
            prev = _BACKWARD_IMPL
            _BACKWARD_IMPL = "pallas"
            ctx = (jax.default_matmul_precision("highest") if pin
                   else contextlib.nullcontext())

            def sqsum(fn):
                return lambda *a: (fn(*a) ** 2).sum()

            try:
                with ctx:
                    k = deform_conv2d_pallas(
                        x, off, mask, wt, interpret=False
                    )
                    j = _dcn_jnp.deform_conv2d(x, off, mask, wt)
                    gk = jax.grad(
                        sqsum(lambda *a: deform_conv2d_pallas(
                            *a, interpret=False)),
                        argnums=(0, 1, 2, 3),
                    )(x, off, mask, wt)
                    gj = jax.grad(
                        sqsum(_dcn_jnp.deform_conv2d), argnums=(0, 1, 2, 3)
                    )(x, off, mask, wt)
                return ([np.asarray(k)] + [np.asarray(g) for g in gk],
                        [np.asarray(j)] + [np.asarray(g) for g in gj])
            finally:
                _BACKWARD_IMPL = prev

        k_hi, j_hi = _probe(True)
        k_def, j_def = _probe(False)

        def max_rel_sens(hi, de):
            worst = 0.0
            for a, b_ in zip(hi, de):
                scale = max(float(np.max(np.abs(a))),
                            float(np.max(np.abs(b_))), 1e-6)
                worst = max(
                    worst, float(np.max(np.abs(a - b_))) / scale
                )
            return worst

        kernel_sens = max_rel_sens(k_hi, k_def)
        jnp_sens = max_rel_sens(j_hi, j_def)
        # Trichotomy: kernel sensitive to the pin => pin honored => the
        # strict failure is a real defect. Kernel insensitive => the pin
        # never reached the kernel's dots — whether jnp moved (pin ignored
        # for the kernel only) or not (pin a global no-op on this
        # backend), the pinned comparison proved nothing about the kernel.
        pin_ignored = kernel_sens < 1e-7
        if not pin_ignored:
            raise AssertionError(
                f"mosaic parity mismatch under pinned precision (kernel "
                f"precision-sensitivity {kernel_sens:.2e}, jnp "
                f"{jnp_sens:.2e} — pin honored, so this is a kernel "
                f"defect, not rounding): {errs}"
            )
        # Bit-stability alone is ALSO the signature of a deterministic
        # kernel defect, so before accepting the looser tolerance run the
        # backend-independent defect screen: the same kernel trace in
        # interpret mode ON THE CPU DEVICE computes f32-exact (no MXU, no
        # pin semantics) and must agree with the jnp formulation to the
        # strict 1e-3 — a real indexing/weighting bug fails here no matter
        # what the TPU backend does with precision requests.
        cpu_dev = jax.devices("cpu")[0]
        cpu_args = [jax.device_put(a, cpu_dev) for a in (x, off, mask, wt)]
        with jax.default_device(cpu_dev):
            errs_cpu = dcn_parity_errors(*cpu_args, interpret=True)
        if not dcn_parity_ok(errs_cpu, tol=1e-3):
            raise AssertionError(
                f"kernel formulation defect: f32-exact CPU interpret "
                f"parity failed the strict tolerance: {errs_cpu}"
            )
        warnings.warn(
            f"Pallas DCN: backend ignored the matmul-precision pin for "
            f"the kernel (kernel bit-stable across modes; jnp reference "
            f"sensitivity {jnp_sens:.2e}); CPU-exact defect screen "
            f"passed; re-checking under matched production numerics",
            stacklevel=2,
        )
        errs = dcn_parity_errors(
            x, off, mask, wt, interpret=False, matmul_precision=None
        )
        if not dcn_parity_ok(errs, matmul_precision=None):
            raise AssertionError(f"mosaic parity mismatch: {errs}")
        _GATE_MODE = ("default-precision fallback tol=2e-2 "
                      "(precision pin ignored by kernel)")
        _GATE_FALLBACK = True
        return True
    except Exception as e:  # noqa: BLE001 - any rejection means "don't use"
        _GATE_MODE = f"failed: {e!r}"
        warnings.warn(
            f"Pallas DCN failed the Mosaic self-test; auto dispatch falls "
            f"back to the jnp formulation: {e!r}",
            stacklevel=2,
        )
        return False


# Forward-direction gate bookkeeping, mirroring _GATE_MODE for the
# train-direction gate. None until pallas_fwd_compiles() has run.
_FWD_GATE_MODE: Optional[str] = None


def fwd_gate_mode() -> Optional[str]:
    """Which parity mode the forward-direction dispatch gate passed (or
    None / a ``failed: ...`` string). Display-only, like
    :func:`gate_mode`."""
    return _FWD_GATE_MODE


@functools.lru_cache(maxsize=None)
def pallas_fwd_compiles() -> bool:
    """Has the DCNv4-style fused FORWARD kernel passed a real Mosaic
    compile+exec this process?

    The forward-direction twin of :func:`pallas_compiles`, gating the
    serving-hot dispatch direction independently (a single gate would
    ship a forward regression to serving the moment train parity
    passes — the r4 capture measured exactly that shape: train 3.17x,
    fwd 0.961). Compiles :func:`deform_conv2d_pallas_fwd` with
    ``interpret=False`` at a tiny shape and checks forward parity against
    the jnp formulation under the pinned-precision, scale-normalized
    criterion (:func:`dcn_fwd_parity_ok` — the same tolerance ladder as
    the train gate; no cotangent checks because this kernel's backward is
    the already-gated train-direction one). The production-numerics
    fallback follows the train gate's trichotomy: reachable only when the
    kernel is bit-stable across precision modes (pin never reached its
    dots) AND the f32-exact CPU-interpret defect screen passes at 1e-3.
    Memoized; False off-TPU."""
    global _FWD_GATE_MODE
    if not on_tpu_backend():
        _FWD_GATE_MODE = "off-tpu (gate closed)"
        return False
    import contextlib
    import warnings

    import numpy as np

    try:
        rng = np.random.default_rng(0)
        b, h, w, c, dg = 1, 4, 6, 16, 2
        x = jnp.asarray(rng.standard_normal((b, h, w, c)), jnp.float32)
        off = jnp.asarray(
            rng.standard_normal((b, h, w, dg, 9, 2)), jnp.float32
        )
        mask = jax.nn.sigmoid(
            jnp.asarray(rng.standard_normal((b, h, w, dg, 9)), jnp.float32)
        )
        wt = jnp.asarray(
            rng.standard_normal((3, 3, c, c)) * 0.1, jnp.float32
        )

        errs = dcn_fwd_parity_errors(x, off, mask, wt, interpret=False)
        if dcn_fwd_parity_ok(errs):
            _FWD_GATE_MODE = (
                "matmul_precision=highest (scale-normalized fwd parity)"
            )
            return True

        # Strict check failed: legitimate only if the backend ignored the
        # precision pin for the kernel (bit-stable across modes), AND the
        # backend-independent defect screen passes — same trichotomy as
        # pallas_compiles, forward fields only.
        def _run(pin):
            ctx = (jax.default_matmul_precision("highest") if pin
                   else contextlib.nullcontext())
            with ctx:
                return np.asarray(deform_conv2d_pallas_fwd(
                    x, off, mask, wt, interpret=False))

        k_hi, k_def = _run(True), _run(False)
        scale = max(float(np.max(np.abs(k_hi))),
                    float(np.max(np.abs(k_def))), 1e-6)
        kernel_sens = float(np.max(np.abs(k_hi - k_def))) / scale
        if kernel_sens >= 1e-7:
            raise AssertionError(
                f"fwd parity mismatch under pinned precision (kernel "
                f"precision-sensitivity {kernel_sens:.2e} — pin honored, "
                f"so this is a kernel defect, not rounding): {errs}"
            )
        cpu_dev = jax.devices("cpu")[0]
        cpu_args = [jax.device_put(a, cpu_dev) for a in (x, off, mask, wt)]
        with jax.default_device(cpu_dev):
            errs_cpu = dcn_fwd_parity_errors(*cpu_args, interpret=True)
        if not dcn_fwd_parity_ok(errs_cpu, tol=1e-3):
            raise AssertionError(
                f"fwd kernel formulation defect: f32-exact CPU interpret "
                f"parity failed the strict tolerance: {errs_cpu}"
            )
        warnings.warn(
            "Pallas DCN fwd: backend ignored the matmul-precision pin for "
            "the kernel (bit-stable across modes); CPU-exact defect screen "
            "passed; re-checking under matched production numerics",
            stacklevel=2,
        )
        errs = dcn_fwd_parity_errors(
            x, off, mask, wt, interpret=False, matmul_precision=None
        )
        if not dcn_fwd_parity_ok(errs, matmul_precision=None):
            raise AssertionError(f"fwd parity mismatch: {errs}")
        _FWD_GATE_MODE = ("default-precision fallback "
                          "(precision pin ignored by kernel)")
        return True
    except Exception as e:  # noqa: BLE001 - any rejection means "don't use"
        _FWD_GATE_MODE = f"failed: {e!r}"
        warnings.warn(
            f"Pallas DCN fwd kernel failed the Mosaic self-test; "
            f"forward-direction auto dispatch stays on the jnp "
            f"formulation: {e!r}",
            stacklevel=2,
        )
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def deform_conv2d_pallas_fwd(
    x: jax.Array,
    offsets: jax.Array,
    mask: jax.Array,
    weight: jax.Array,
    bias: Optional[jax.Array] = None,
    stride: int = 1,
    padding: int = 1,
    dilation: int = 1,
    interpret: Optional[bool] = None,
    tile_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """DCNv4-style fused forward (:func:`_dcn_fwd_kernel`) — the
    serving-direction fast path. Same signature and dtype contract as
    :func:`deform_conv2d_pallas`; differentiable for completeness (the
    VJP delegates to the SAME fused backward as the train-direction op),
    but train-direction dispatch keeps :func:`deform_conv2d_pallas` so
    train numerics are byte-for-byte untouched by this kernel.

    ``tile_mask`` (optional f32, ``[B]`` or ``[B, n_tiles]``): activity
    bitmap for block predication — inactive (image, tile) programs skip
    their gather+MXU loop (:func:`_dcn_fwd_kernel_masked`). The caller
    asserts that everything a masked-off tile could sample is zero;
    :func:`dcn_image_activity` derives the always-safe per-image form.
    ``None`` (default) builds the byte-identical dense program."""
    interp = _auto_interpret() if interpret is None else interpret
    out = _pallas_forward_fused(
        x, offsets, mask, weight, stride, padding, dilation, interp,
        tile_mask=tile_mask,
    )
    if bias is not None:
        out = out + bias
    return out.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def deform_conv2d_pallas(
    x: jax.Array,
    offsets: jax.Array,
    mask: jax.Array,
    weight: jax.Array,
    bias: Optional[jax.Array] = None,
    stride: int = 1,
    padding: int = 1,
    dilation: int = 1,
    interpret: Optional[bool] = None,
    tile_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Drop-in replacement for :func:`esr_tpu.ops.dcn.deform_conv2d` with the
    fused Pallas forward. ``interpret=None`` auto-selects interpreter mode on
    CPU backends (tests) and compiled Mosaic on TPU. ``tile_mask`` enables
    activity block predication of the PRIMAL forward only (the fused
    backward stays dense — ``gx`` of a zero block is not zero); ``None``
    builds the byte-identical dense program."""
    interp = _auto_interpret() if interpret is None else interpret
    out = _pallas_forward(
        x, offsets, mask, weight, stride, padding, dilation, interp,
        tile_mask=tile_mask,
    )
    if bias is not None:
        out = out + bias
    # Accumulation is f32 inside the kernel; the public output follows the
    # input dtype so the op composes with bf16 mixed-precision pipelines
    # exactly like the jnp formulation (whose output dtype is x.dtype).
    return out.astype(x.dtype)


def _dcn_bwd_kernel(
    xt_ref, idx_ref, wgt_ref, wt_ref, gt_ref,
    gxt_ref, gw_ref, gwgt_ref,
    *, dg, cg, k, hw_pad, no_tile, cout,
):
    """One (batch image, output tile) per program. Rebuilds each (group,
    tap) S matrix and emits all three cotangents with MXU contractions:
    ``grad_cols = Wᵀg``, ``gxᵀ += grad_cols·Sᵀ`` (col2im as matmul),
    ``gw += (imgᵀ·S)·gᵀ``, and the corner-weight cotangents via the one-hot
    trick reduced over rows. ``gxt`` accumulates across output tiles (same
    block revisited over t), ``gw`` across the whole grid."""
    from jax.experimental import pallas as pl

    HIGH = jax.lax.Precision.HIGHEST
    b_i = pl.program_id(0)
    t_i = pl.program_id(1)
    iota = jax.lax.broadcasted_iota(jnp.int32, (hw_pad, no_tile), 0)

    @pl.when(t_i == 0)
    def _init_gx():
        gxt_ref[0] = jnp.zeros_like(gxt_ref[0])

    @pl.when((b_i == 0) & (t_i == 0))
    def _init_gw():
        gw_ref[...] = jnp.zeros_like(gw_ref[...])

    gt_b = gt_ref[0]  # [Cout, no_tile]

    def body(i, carry):
        g = i // k
        kk = i % k
        img_g = xt_ref[0, pl.ds(g * cg, cg), :]  # [Cg, HWp]
        s = jnp.zeros((hw_pad, no_tile), jnp.float32)
        for c in range(4):
            iv = idx_ref[0, g, c, kk, :]
            wv = wgt_ref[0, g, c, kk, :]
            s = s + jnp.where(iota == iv[None, :], wv[None, :], 0.0)

        # grad_cols [Cg, no_tile] = W[g,kk]ᵀ [Cg, Cout] @ gᵀ [Cout, no_tile]
        gcols = jax.lax.dot_general(
            wt_ref[g, kk], gt_b, (((0,), (0,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32,
        )
        # gxᵀ_g [Cg, HWp] += grad_cols @ Sᵀ  (the col2im scatter as a matmul)
        gx_part = jax.lax.dot_general(
            gcols, s, (((1,), (1,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32,
        )
        gxt_ref[0, pl.ds(g * cg, cg), :] = (
            gxt_ref[0, pl.ds(g * cg, cg), :] + gx_part
        )
        # gw[g,kk] [Cg, Cout] += cols @ gᵀᵀ, cols = imgᵀ_g @ S
        cols = jax.lax.dot_general(
            img_g, s, (((1,), (0,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32,
        )
        gw_part = jax.lax.dot_general(
            cols, gt_b, (((1,), (1,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32,
        )
        gw_ref[g, kk] = gw_ref[g, kk] + gw_part
        # P [HWp, no_tile] = imgᵀ_gᵀ @ grad_cols; corner cotangent =
        # one-hot-selected row sum of P
        p = jax.lax.dot_general(
            img_g, gcols, (((0,), (0,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32,
        )
        for c in range(4):
            iv = idx_ref[0, g, c, kk, :]
            gwgt_ref[0, g, c, kk, :] = jnp.sum(
                jnp.where(iota == iv[None, :], p, 0.0), axis=0
            )
        return carry

    jax.lax.fori_loop(0, dg * k, body, 0)


def _pallas_backward(
    x: jax.Array,
    offsets: jax.Array,
    mask: jax.Array,
    weight: jax.Array,
    g: jax.Array,
    stride: int,
    padding: int,
    dilation: int,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fused gradients ``(gx, goffsets, gmask, gweight)`` — no HBM column
    tensor in the backward either."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, w, cin = x.shape
    kh, kw, _, cout = weight.shape
    _, ho, wo, dg, k, _ = offsets.shape
    in_dtypes = (x.dtype, offsets.dtype, mask.dtype, weight.dtype)
    xf = x.astype(jnp.float32)
    of = offsets.astype(jnp.float32)
    mf = mask.astype(jnp.float32)
    wf = weight.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    cg = cin // dg
    no = ho * wo
    hw_pad, no_tile, no_pad, n_tiles = _tiling(h * w, no)

    idx, wgt = _corner_decomposition(
        of, mf, h, w, stride, padding, dilation, kh, kw, hw_pad, no_pad
    )
    xt = xf.reshape(b, h * w, cin).transpose(0, 2, 1)
    xt = jnp.pad(xt, ((0, 0), (0, 0), (0, hw_pad - h * w)))
    wt = wf.reshape(k, dg, cg, cout).transpose(1, 0, 3, 2)
    gt = gf.reshape(b, no, cout).transpose(0, 2, 1)
    gt = jnp.pad(gt, ((0, 0), (0, 0), (0, no_pad - no)))

    kernel = functools.partial(
        _dcn_bwd_kernel,
        dg=dg, cg=cg, k=k, hw_pad=hw_pad, no_tile=no_tile, cout=cout,
    )
    gxt, gw, gwgt = pl.pallas_call(
        kernel,
        grid=(b, n_tiles),
        in_specs=[
            pl.BlockSpec((1, cin, hw_pad), lambda i, t: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dg, 4, k, no_tile), lambda i, t: (i, 0, 0, 0, t), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dg, 4, k, no_tile), lambda i, t: (i, 0, 0, 0, t), memory_space=pltpu.VMEM),
            pl.BlockSpec((dg, k, cout, cg), lambda i, t: (0, 0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout, no_tile), lambda i, t: (i, 0, t), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, cin, hw_pad), lambda i, t: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((dg, k, cg, cout), lambda i, t: (0, 0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dg, 4, k, no_tile), lambda i, t: (i, 0, 0, 0, t), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, cin, hw_pad), jnp.float32),
            jax.ShapeDtypeStruct((dg, k, cg, cout), jnp.float32),
            jax.ShapeDtypeStruct((b, dg, 4, k, no_pad), jnp.float32),
        ],
        interpret=interpret,
    )(xt, idx, wgt, wt, gt)

    gx = gxt[:, :, : h * w].transpose(0, 2, 1).reshape(b, h, w, cin)
    # [dg, K, Cg, Cout] -> HWIO (cin splits (dg, cg), dg-major — the inverse
    # of the forward's weight packing)
    gweight = gw.transpose(1, 0, 2, 3).reshape(kh, kw, cin, cout)
    # corner cotangents back to natural layout, then VJP through the
    # (differentiable) corner-weight computation for offset/mask grads
    gwgt_nat = (
        gwgt[..., :no]
        .transpose(0, 4, 1, 3, 2)
        .reshape(b, ho, wo, dg, k, 4)
    )

    def wgt_fn(off_, mask_):
        return _corner_pairs(
            off_, mask_, h, w, stride, padding, dilation, kh, kw
        )[1]

    _, vjp = jax.vjp(wgt_fn, of, mf)
    goff, gmask = vjp(gwgt_nat)
    return (
        gx.astype(in_dtypes[0]),
        goff.astype(in_dtypes[1]),
        gmask.astype(in_dtypes[2]),
        gweight.astype(in_dtypes[3]),
    )


# Backward implementation selector: 'pallas' (fused, default) or 'jnp' (XLA
# autodiff of the jnp formulation — the oracle the fused path is pinned
# against, and the bench A/B baseline). Read at TRACE time: set it before
# jit-tracing the step you want to measure.
_BACKWARD_IMPL = "pallas"


def dcn_backward_impl(impl: str) -> None:
    global _BACKWARD_IMPL
    assert impl in ("pallas", "jnp"), impl
    _BACKWARD_IMPL = impl


def _fwd(x, offsets, mask, weight, bias, stride, padding, dilation,
         interpret, tile_mask):
    out = deform_conv2d_pallas(
        x, offsets, mask, weight, bias, stride, padding, dilation,
        interpret, tile_mask,
    )
    return out, (x, offsets, mask, weight, bias, tile_mask)


def _bwd(stride, padding, dilation, interpret, res, g):
    x, offsets, mask, weight, bias, tile_mask = res
    # the mask is a non-differentiable activity annotation: its cotangent
    # is identically zero (predication only ever skips tiles whose dense
    # result is zero, so the primal is mask-independent by construction)
    gtm = None if tile_mask is None else jnp.zeros_like(tile_mask)

    if _BACKWARD_IMPL == "jnp":

        def ref_fn(x_, offsets_, mask_, weight_, bias_):
            return _dcn_jnp.deform_conv2d(
                x_, offsets_, mask_, weight_,
                bias_ if bias is not None else None,
                stride=stride, padding=padding, dilation=dilation,
            )

        primal, vjp = jax.vjp(ref_fn, x, offsets, mask, weight, bias)
        gx, goff, gmask, gw, gb = vjp(g.astype(primal.dtype))
        return gx, goff, gmask, gw, (gb if bias is not None else None), gtm

    interp = _auto_interpret() if interpret is None else interpret
    gx, goff, gmask, gw = _pallas_backward(
        x, offsets, mask, weight, g, stride, padding, dilation, interp
    )
    gb = (
        g.astype(jnp.float32).sum(axis=(0, 1, 2)).astype(bias.dtype)
        if bias is not None
        else None
    )
    return gx, goff, gmask, gw, gb, gtm


deform_conv2d_pallas.defvjp(_fwd, _bwd)


def _fwd_v4(x, offsets, mask, weight, bias, stride, padding, dilation,
            interpret, tile_mask):
    out = deform_conv2d_pallas_fwd(
        x, offsets, mask, weight, bias, stride, padding, dilation,
        interpret, tile_mask,
    )
    return out, (x, offsets, mask, weight, bias, tile_mask)


# The DCNv4-style forward shares the train-direction op's fused backward
# verbatim (_bwd also honors dcn_backward_impl('jnp') for A/B), so
# differentiating through the fwd-specialized op cannot fork gradient
# numerics from the gated train path.
deform_conv2d_pallas_fwd.defvjp(_fwd_v4, _bwd)
