"""Pallas TPU kernel for modulated deformable convolution (DCNv2).

The fused fast path promised by SURVEY.md §7.1-3 phase B, replacing the
reference's CUDA im2col + GEMM pair
(``/root/reference/models/DCNv2/src/cuda/dcn_v2_im2col_cuda.cu:125+``,
``dcn_v2_cuda.cu:78-92``) — and the HBM round-trip of the jnp fallback
(``esr_tpu.ops.dcn.deform_conv2d`` materializes the ``[B, dg, Ho, Wo, K, Cg]``
column tensor in HBM; this kernel never does).

TPU-native formulation
----------------------
A CUDA-style per-thread scalar gather does not map to the TPU's vector units,
so the bilinear gather is recast as **one-hot matrix multiplication** on the
MXU, operating entirely in VMEM:

- host-side (XLA-fused elementwise): sampling positions = base grid + learned
  offsets; decomposed into 4 integer corner indices (flattened, clipped) and
  4 bilinear corner weights, pre-multiplied by the sigmoid modulation mask and
  zeroed outside the image (the ``dmcn_im2col_bilinear_cuda`` boundary rule);
- kernel, per batch image: for each deformable group ``g`` and kernel tap
  ``k``, build the weighted selection matrix
  ``S[hw, o] = Σ_corners (hw == idx_c[o]) · w_c[o]`` with vector compares
  against an iota (no scatter), then two MXU contractions
  ``colsᵀ = imgᵀ_g · S`` and ``acc += Wᵀ_{g,k} · colsᵀ``;
- matmuls run at ``Precision.HIGHEST``: the MXU's default bf16 rounding is a
  *gather corruption* here (values, not just precision, change) — verified
  exact against ``jnp.take`` at f32.

Everything lives in VMEM for one batch image (feature maps at the ESR
bottleneck are tiny: ``H/8 × W/8 × 8·basech``), so the only HBM traffic is
the input read and output write.

The backward pass is the jnp formulation's VJP via ``jax.custom_vjp`` — the
transpose of the gather is exactly the reference's atomicAdd col2im scatter
(``dcn_v2_im2col_cuda.cu:56-123``), and XLA autodiff of the gather emits it.
Gradients are therefore bit-identical to the jnp path the tests pin.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from esr_tpu.ops import dcn as _dcn_jnp


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _corner_decomposition(
    offsets: jax.Array,
    mask: jax.Array,
    h: int,
    w: int,
    stride: int,
    padding: int,
    dilation: int,
    kh: int,
    kw: int,
    hw_pad: int,
    no_pad: int,
) -> Tuple[jax.Array, jax.Array]:
    """Sampling positions -> 4 (index, weight) corner pairs per tap.

    Returns ``idx [B, dg, 4, K, No_pad] int32`` and
    ``wgt [B, dg, 4, K, No_pad] f32`` (mask-premultiplied, zero when the
    corner falls outside the image or in the No padding).
    """
    b, ho, wo, dg, k, _ = offsets.shape
    no = ho * wo

    oy = jnp.arange(ho) * stride - padding
    ox = jnp.arange(wo) * stride - padding
    ky, kx = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
    tap_y = (ky * dilation).reshape(-1).astype(jnp.float32)
    tap_x = (kx * dilation).reshape(-1).astype(jnp.float32)

    base_y = oy[:, None, None, None].astype(jnp.float32) + tap_y[None, None, None, :]
    base_x = ox[None, :, None, None].astype(jnp.float32) + tap_x[None, None, None, :]
    ys = base_y[None] + offsets[..., 0]  # [B, Ho, Wo, dg, K]
    xs = base_x[None] + offsets[..., 1]

    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    dy = ys - y0
    dx = xs - x0

    idxs, wgts = [], []
    for cy, cx, cw in (
        (0, 0, (1 - dy) * (1 - dx)),
        (0, 1, (1 - dy) * dx),
        (1, 0, dy * (1 - dx)),
        (1, 1, dy * dx),
    ):
        yi = y0.astype(jnp.int32) + cy
        xi = x0.astype(jnp.int32) + cx
        inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        flat = jnp.clip(yi, 0, h - 1) * w + jnp.clip(xi, 0, w - 1)
        idxs.append(jnp.where(inb, flat, 0))
        wgts.append(jnp.where(inb, cw, 0.0) * mask)

    # [B, Ho, Wo, dg, K, 4] -> [B, dg, 4, K, No]
    idx = jnp.stack(idxs, axis=-1)
    wgt = jnp.stack(wgts, axis=-1)
    idx = idx.reshape(b, no, dg, k, 4).transpose(0, 2, 4, 3, 1)
    wgt = wgt.reshape(b, no, dg, k, 4).transpose(0, 2, 4, 3, 1)

    idx = jnp.pad(idx, ((0, 0), (0, 0), (0, 0), (0, 0), (0, no_pad - no)))
    wgt = jnp.pad(wgt, ((0, 0), (0, 0), (0, 0), (0, 0), (0, no_pad - no)))
    return idx.astype(jnp.int32), wgt.astype(jnp.float32)


def _dcn_kernel(xt_ref, idx_ref, wgt_ref, wt_ref, out_ref, *, dg, cg, k, hw_pad, no_tile, cout):
    """One (batch image, output tile) per program; ``fori_loop`` over the
    flattened (group, tap) pairs keeps VMEM to one S matrix at a time and
    writes the f32 accumulator exactly once."""
    from jax.experimental import pallas as pl

    HIGH = jax.lax.Precision.HIGHEST
    iota = jax.lax.broadcasted_iota(jnp.int32, (hw_pad, no_tile), 0)

    def body(i, acc):
        g = i // k
        kk = i % k
        img_g = xt_ref[0, pl.ds(g * cg, cg), :]  # [Cg, HWp]
        s = jnp.zeros((hw_pad, no_tile), jnp.float32)
        for c in range(4):
            iv = idx_ref[0, g, c, kk, :]  # [no_tile] lane vector
            wv = wgt_ref[0, g, c, kk, :]
            s = s + jnp.where(iota == iv[None, :], wv[None, :], 0.0)
        # colsT [Cg, no_tile] = imgT_g [Cg, HWp] @ S [HWp, no_tile]
        cols = jax.lax.dot_general(
            img_g, s, (((1,), (0,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32,
        )
        # acc [Cout, no_tile] += Wt[g, kk] [Cout, Cg] @ colsT
        return acc + jax.lax.dot_general(
            wt_ref[g, kk], cols, (((1,), (0,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32,
        )

    out_ref[0] = jax.lax.fori_loop(
        0, dg * k, body, jnp.zeros((cout, no_tile), jnp.float32)
    )


def _pallas_forward(
    x: jax.Array,
    offsets: jax.Array,
    mask: jax.Array,
    weight: jax.Array,
    stride: int,
    padding: int,
    dilation: int,
    interpret: bool,
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, w, cin = x.shape
    kh, kw, wcin, cout = weight.shape
    _, ho, wo, dg, k, _ = offsets.shape
    assert wcin == cin and k == kh * kw and cin % dg == 0
    # The kernel is the accuracy-oriented path: all operands f32 (Mosaic
    # rejects mixed-dtype dots, and the one-hot S matmul wants f32 anyway).
    # Callers in bf16 pipelines get their dtype restored by the wrapper.
    x = x.astype(jnp.float32)
    offsets = offsets.astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    weight = weight.astype(jnp.float32)
    cg = cin // dg
    no = ho * wo
    hw_pad = _round_up(h * w, 128)
    # Output-pixel tiling bounds the S matrix (and iota) to
    # [hw_pad, no_tile] f32 in VMEM; shrink the tile as the image grows.
    if hw_pad <= 1024:
        cap = 512
    elif hw_pad <= 4096:
        cap = 256
    else:
        cap = 128
    no_tile = min(cap, _round_up(no, 128))
    no_pad = _round_up(no, no_tile)
    n_tiles = no_pad // no_tile

    idx, wgt = _corner_decomposition(
        offsets, mask, h, w, stride, padding, dilation, kh, kw, hw_pad, no_pad
    )

    # x [B, H, W, C] -> xT [B, C, HWp]
    xt = x.reshape(b, h * w, cin).transpose(0, 2, 1)
    xt = jnp.pad(xt, ((0, 0), (0, 0), (0, hw_pad - h * w)))
    # weight HWIO -> [dg, K, Cout, Cg]
    wt = weight.reshape(k, dg, cg, cout).transpose(1, 0, 3, 2)

    kernel = functools.partial(
        _dcn_kernel, dg=dg, cg=cg, k=k, hw_pad=hw_pad, no_tile=no_tile, cout=cout
    )
    out_t = pl.pallas_call(
        kernel,
        grid=(b, n_tiles),
        in_specs=[
            pl.BlockSpec((1, cin, hw_pad), lambda i, t: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dg, 4, k, no_tile), lambda i, t: (i, 0, 0, 0, t), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dg, 4, k, no_tile), lambda i, t: (i, 0, 0, 0, t), memory_space=pltpu.VMEM),
            pl.BlockSpec((dg, k, cout, cg), lambda i, t: (0, 0, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, cout, no_tile), lambda i, t: (i, 0, t), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b, cout, no_pad), jnp.float32),
        interpret=interpret,
    )(xt, idx, wgt, wt)

    # [B, Cout, Nop] -> [B, Ho, Wo, Cout]
    return out_t[:, :, :no].transpose(0, 2, 1).reshape(b, ho, wo, cout)


def _auto_interpret() -> bool:
    return jax.default_backend() == "cpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def deform_conv2d_pallas(
    x: jax.Array,
    offsets: jax.Array,
    mask: jax.Array,
    weight: jax.Array,
    bias: Optional[jax.Array] = None,
    stride: int = 1,
    padding: int = 1,
    dilation: int = 1,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Drop-in replacement for :func:`esr_tpu.ops.dcn.deform_conv2d` with the
    fused Pallas forward. ``interpret=None`` auto-selects interpreter mode on
    CPU backends (tests) and compiled Mosaic on TPU."""
    interp = _auto_interpret() if interpret is None else interpret
    out = _pallas_forward(x, offsets, mask, weight, stride, padding, dilation, interp)
    if bias is not None:
        out = out + bias
    # Accumulation is f32 inside the kernel; the public output follows the
    # input dtype so the op composes with bf16 mixed-precision pipelines
    # exactly like the jnp formulation (whose output dtype is x.dtype).
    return out.astype(x.dtype)


def _fwd(x, offsets, mask, weight, bias, stride, padding, dilation, interpret):
    out = deform_conv2d_pallas(
        x, offsets, mask, weight, bias, stride, padding, dilation, interpret
    )
    return out, (x, offsets, mask, weight, bias)


def _bwd(stride, padding, dilation, interpret, res, g):
    x, offsets, mask, weight, bias = res

    def ref_fn(x_, offsets_, mask_, weight_, bias_):
        return _dcn_jnp.deform_conv2d(
            x_, offsets_, mask_, weight_,
            bias_ if bias is not None else None,
            stride=stride, padding=padding, dilation=dilation,
        )

    primal, vjp = jax.vjp(ref_fn, x, offsets, mask, weight, bias)
    gx, goff, gmask, gw, gb = vjp(g.astype(primal.dtype))
    return gx, goff, gmask, gw, (gb if bias is not None else None)


deform_conv2d_pallas.defvjp(_fwd, _bwd)
