"""Span-based step-time attribution: where does a super-step's wall go?

PR 2 shipped K-step fused training on the strength of one hand-timed bench
number; this module makes the attribution permanent. The Trainer drives a
:class:`StepAttribution` through its loop and every super-step produces one
``attribution`` record decomposing host wall-clock into named spans:

- ``data_wait``      blocked pulling the next batch group from the loader /
                     prefetcher queue;
- ``stage_megabatch`` host->device staging of the group. When the
                     ``DevicePrefetcher`` stages on its producer thread the
                     span is recorded as *overlapped* (it runs concurrently
                     with earlier steps' device compute) and excluded from
                     the wall-clock accounting identity below;
- ``dispatch``       the jitted call itself — tracing + XLA compilation land
                     here on (re)trace, microseconds on cache hits;
- ``device_step``    NON-BLOCKING device-time estimate: timestamped at
                     dispatch return, resolved when the existing
                     cadence-gated scalar readback observes the metrics —
                     no new host syncs enter the hot loop;
- ``metric_readback`` the host-blocked portion of that readback (a tail
                     *inside* ``device_step``, reported separately, never
                     double-counted);
- ``checkpoint`` / ``validate``  the cadence-gated save / validation pass;
- ``residual``       ``wall − accounted`` — everything unattributed
                     (cadence bookkeeping, logging, lr-schedule eval).

Accounting identity (see docs/OBSERVABILITY.md for the full read-me):

    wall ≈ data_wait + stage_megabatch(inline) + dispatch + device_step
           + checkpoint + validate + residual

Strict with ``train_lookahead: 0`` / ``device_prefetch: 0`` (the
``scripts/obs_smoke.sh`` configuration asserts |residual| ≤ 5% of wall);
under lookahead/prefetch the device span overlaps later iterations' host
work by design, so ``residual`` can go negative and ``goodput`` is clamped.

Derived per record: ``samples_per_sec`` (host-local sequences/s over the
super-step) and ``goodput`` = device_step / wall ∈ (0, 1].

Everything here is host-side and stdlib-only; nothing may be called from
traced code (analysis rule ESR007).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from esr_tpu.obs import trace


class StepSpans:
    """One super-step's span bucket.

    Created by :meth:`StepAttribution.begin`, carried through the Trainer's
    ``pending`` deque alongside the in-flight metrics, finalized when both
    the loop body closed it (wall-clock end) AND the metrics readback
    resolved it (device span end) — whichever happens last emits.

    v2 (docs/OBSERVABILITY.md "Schema v2"): the bucket carries a trace
    identity from birth — ``span_id`` is the super-step ROOT span, parented
    under the ambient context at :meth:`StepAttribution.begin` time (the
    Trainer's ``train_run`` span), and every :meth:`measure` block records
    its begin/end edges (``marks``) so emission can produce properly
    nested child spans, not just duration sums. The dispatch wrapper
    (``training/multistep.instrument_dispatch``) adopts :attr:`ctx` around
    the jitted call, which is how ``compile`` events land INSIDE the
    super-step's trace.
    """

    __slots__ = (
        "first", "k", "t0", "t_close", "t_dispatch", "t_resolved",
        "spans", "overlapped", "readback_s", "emitted",
        "trace_id", "span_id", "parent_id", "marks",
    )

    def __init__(self, t0: float, trace_id: str, parent_id: Optional[str]):
        self.first: Optional[int] = None
        self.k: int = 0
        self.t0 = t0
        self.t_close: Optional[float] = None
        self.t_dispatch: Optional[float] = None
        self.t_resolved: Optional[float] = None
        self.spans: Dict[str, float] = {}
        self.overlapped: set = set()
        self.readback_s = 0.0
        self.emitted = False
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.span_id = trace.new_id()
        self.marks: Dict[str, List[Tuple[float, float]]] = {}

    @property
    def ctx(self) -> trace.TraceContext:
        """The context child records adopt to join this super-step."""
        return trace.TraceContext(self.trace_id, self.span_id)

    def add(self, name: str, seconds: float, overlapped: bool = False):
        self.spans[name] = self.spans.get(name, 0.0) + float(seconds)
        if overlapped:
            self.overlapped.add(name)

    def mark(self, name: str, t0: float, t1: float):
        """Record one timed block's clock edges (same clock as ``t0``)."""
        self.marks.setdefault(name, []).append((t0, t1))


class StepAttribution:
    """Per-super-step wall-clock attribution driver (host-side).

    Every method is a no-op-safe cheap host operation: with no open bucket
    (or no sink) instrumented call sites cost a ``None`` check, so wrapped
    steps stay usable outside the training loop (tests, bench).
    """

    def __init__(
        self,
        sink=None,
        batch_size: int = 1,
        log_step: int = 1,
        clock=time.monotonic,
    ):
        self.sink = sink
        self.batch_size = max(int(batch_size), 1)
        self.log_step = max(int(log_step), 1)
        self._clock = clock
        self.current: Optional[StepSpans] = None
        self.emitted_records = 0
        # one trace per attribution driver (i.e. per train run) when no
        # ambient trace encloses the loop; under an ambient span (the
        # Trainer's `train_run`) buckets join ITS trace instead
        self._trace_id: Optional[str] = None

    # -- super-step lifecycle ---------------------------------------------

    def begin(self) -> StepSpans:
        """Open a fresh bucket at the top of a loop iteration; the bucket
        is born with a trace identity — a child of the ambient span when
        one is open (the Trainer's ``train_run``)."""
        ambient = trace.current()
        if ambient is not None:
            trace_id, parent_id = ambient.trace_id, ambient.span_id
        else:
            if self._trace_id is None:
                self._trace_id = trace.new_id()
            trace_id, parent_id = self._trace_id, None
        self.current = StepSpans(self._clock(), trace_id, parent_id)
        return self.current

    def discard(self) -> None:
        """Drop an empty bucket (source exhausted before a group arrived)."""
        self.current = None

    def current_ctx(self) -> Optional[trace.TraceContext]:
        """The open bucket's trace context, or None — THE way work done
        on a super-step's behalf (the instrumented dispatch, checkpoint
        snapshot/commit) joins its trace via ``trace.adopt``."""
        cur = self.current
        return cur.ctx if cur is not None else None

    def note(self, first: int, k: int) -> None:
        """Record which iterations this super-step covers."""
        if self.current is not None:
            self.current.first = int(first)
            self.current.k = int(k)

    def close(self) -> None:
        """Mark the wall-clock end of the loop body; detaches the bucket
        (it lives on in the pending entry until the readback resolves it).
        Idempotent."""
        cur = self.current
        if cur is None:
            return
        if cur.t_close is None:
            cur.t_close = self._clock()
        self.current = None
        self._maybe_emit(cur)

    # -- span recording ----------------------------------------------------

    @contextmanager
    def measure(self, name: str):
        """Time a block into the current bucket (nested/overlapping blocks
        each record their full duration under their own name)."""
        cur = self.current
        if cur is None:
            yield
            return
        t0 = self._clock()
        try:
            yield
        finally:
            t1 = self._clock()
            cur.add(name, t1 - t0)
            cur.mark(name, t0, t1)

    def add(self, name: str, seconds: float, overlapped: bool = False):
        if self.current is not None:
            self.current.add(name, seconds, overlapped=overlapped)

    def dispatched(self) -> None:
        """Timestamp the (async) dispatch of this super-step's device work."""
        if self.current is not None:
            self.current.t_dispatch = self._clock()

    @contextmanager
    def resolving(self, bucket: Optional[StepSpans]):
        """Wrap the cadence-gated scalar readback that forces the device
        sync: the block duration is the host-blocked ``metric_readback``;
        its end resolves the non-blocking ``device_step`` span."""
        if bucket is None:
            yield
            return
        t0 = self._clock()
        try:
            yield
        finally:
            now = self._clock()
            bucket.readback_s += now - t0
            bucket.mark("metric_readback", t0, now)
            bucket.t_resolved = now
            self._maybe_emit(bucket)

    # -- emission ----------------------------------------------------------

    def record(self, bucket: StepSpans) -> Dict:
        """The attribution record for a finalized bucket (field order is
        the published schema — docs/OBSERVABILITY.md)."""
        # wall is the loop-BODY's span (t_close); under lookahead the
        # readback lands later and device work overlaps the next
        # iterations by design — t_resolved never extends the wall
        if bucket.t_close is not None:
            end = bucket.t_close
        elif bucket.t_resolved is not None:
            end = bucket.t_resolved
        else:
            end = self._clock()
        wall = max(end - bucket.t0, 1e-9)
        device = 0.0
        if bucket.t_dispatch is not None and bucket.t_resolved is not None:
            device = max(bucket.t_resolved - bucket.t_dispatch, 0.0)
        spans = bucket.spans
        accounted = device + sum(
            v for n, v in spans.items() if n not in bucket.overlapped
        )
        k = bucket.k or 1
        return {
            "first_iteration": bucket.first,
            "k": k,
            "wall_s": round(wall, 6),
            "data_wait_s": round(spans.get("data_wait", 0.0), 6),
            "stage_megabatch_s": round(spans.get("stage_megabatch", 0.0), 6),
            "stage_overlapped": "stage_megabatch" in bucket.overlapped,
            "dispatch_s": round(spans.get("dispatch", 0.0), 6),
            "device_step_s": round(device, 6),
            "metric_readback_s": round(bucket.readback_s, 6),
            "checkpoint_s": round(spans.get("checkpoint", 0.0), 6),
            "validate_s": round(spans.get("validate", 0.0), 6),
            "residual_s": round(wall - accounted, 6),
            "samples_per_sec": round(k * self.batch_size / wall, 3),
            "goodput": round(min(max(device / wall, 1e-9), 1.0), 6),
            # v2 trace linkage, trailing so the v1 column order is a
            # strict prefix: span_id IS the super_step root span below
            "trace_id": bucket.trace_id,
            "span_id": bucket.span_id,
            "parent_id": bucket.parent_id,
        }

    def _due(self, bucket: StepSpans) -> bool:
        """Emission snaps to the ``train_log_step`` cadence exactly like
        the Trainer's loss line: due when ANY covered iteration hits it."""
        if bucket.first is None:
            return False
        return any(
            (bucket.first + j) % self.log_step == 0 for j in range(bucket.k)
        )

    def _maybe_emit(self, bucket: StepSpans) -> None:
        # a bucket emits once, after BOTH wall end and readback are known:
        # lookahead=0 resolves mid-body and emits at close; lookahead>0
        # closes first and emits at the deferred readback.
        if bucket.emitted:
            return
        if bucket.t_close is None or bucket.t_resolved is None:
            return
        bucket.emitted = True
        due = self._due(bucket)
        if not due:
            # root-only emission: components that ADOPTED this bucket's
            # context (compile events inside the dispatch, checkpoint
            # snapshot/commit) reference its span_id as parent — the root
            # span must exist in the file for EVERY super-step or those
            # links dangle; the attribution record and the child span
            # tree stay behind the train_log_step cadence.
            if self.sink is not None:
                self._emit_root(bucket, self.record(bucket))
            return
        rec = self.record(bucket)
        self.emitted_records += 1
        if self.sink is not None:
            self.sink.attribution(rec)
            self._emit_trace_spans(bucket, rec)

    def _edge_conv(self):
        """Clock edges translate onto the sink's ``t`` axis only when
        this driver runs on the real monotonic clock (the production
        configuration); under an injected test clock spans carry
        durations only — same contract as v1 spans."""
        return self.sink.rel if self._clock is time.monotonic else None

    def _emit_root(self, bucket: StepSpans, rec: Dict) -> None:
        conv = self._edge_conv()
        end = bucket.t_close if bucket.t_close is not None else bucket.t0
        edges = ({} if conv is None else
                 {"begin": round(conv(bucket.t0), 6),
                  "end": round(conv(end), 6)})
        self.sink.span(
            "super_step", max(end - bucket.t0, 0.0),
            trace_id=bucket.trace_id, span_id=bucket.span_id,
            parent_id=bucket.parent_id,
            first_iteration=bucket.first, k=bucket.k or 1,
            goodput=rec["goodput"],
            **edges,
        )

    def _emit_trace_spans(self, bucket: StepSpans, rec: Dict) -> None:
        """The bucket as a span tree: one ``super_step`` root plus one
        child per named attribution block (docs/OBSERVABILITY.md v2).

        Children are emitted at the same ``train_log_step`` cadence as
        the attribution record, so trace volume scales with the logging
        budget, not the step count (the root alone is emitted for every
        super-step — see :meth:`_maybe_emit`).
        """
        sink = self.sink
        conv = self._edge_conv()

        def _edges(t0, t1):
            if conv is None or t0 is None or t1 is None:
                return {}
            return {"begin": round(conv(t0), 6), "end": round(conv(t1), 6)}

        self._emit_root(bucket, rec)
        for name, edges in bucket.marks.items():
            over = {"overlapped": True} if name in bucket.overlapped else {}
            for t0, t1 in edges:
                sink.span(
                    name, t1 - t0,
                    trace_id=bucket.trace_id, span_id=trace.new_id(),
                    parent_id=bucket.span_id,
                    **over, **_edges(t0, t1),
                )
        # buckets recorded via add() only (the prefetcher's producer-thread
        # staging parks a duration, no edges) still surface as children
        for name in bucket.spans:
            if name in bucket.marks:
                continue
            over = {"overlapped": True} if name in bucket.overlapped else {}
            sink.span(
                name, bucket.spans[name],
                trace_id=bucket.trace_id, span_id=trace.new_id(),
                parent_id=bucket.span_id, **over,
            )
        if bucket.t_dispatch is not None and bucket.t_resolved is not None:
            sink.span(
                "device_step",
                max(bucket.t_resolved - bucket.t_dispatch, 0.0),
                trace_id=bucket.trace_id, span_id=trace.new_id(),
                parent_id=bucket.span_id,
                **_edges(bucket.t_dispatch, bucket.t_resolved),
            )
