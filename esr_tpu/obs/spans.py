"""Span-based step-time attribution: where does a super-step's wall go?

PR 2 shipped K-step fused training on the strength of one hand-timed bench
number; this module makes the attribution permanent. The Trainer drives a
:class:`StepAttribution` through its loop and every super-step produces one
``attribution`` record decomposing host wall-clock into named spans:

- ``data_wait``      blocked pulling the next batch group from the loader /
                     prefetcher queue;
- ``stage_megabatch`` host->device staging of the group. When the
                     ``DevicePrefetcher`` stages on its producer thread the
                     span is recorded as *overlapped* (it runs concurrently
                     with earlier steps' device compute) and excluded from
                     the wall-clock accounting identity below;
- ``dispatch``       the jitted call itself — tracing + XLA compilation land
                     here on (re)trace, microseconds on cache hits;
- ``device_step``    NON-BLOCKING device-time estimate: timestamped at
                     dispatch return, resolved when the existing
                     cadence-gated scalar readback observes the metrics —
                     no new host syncs enter the hot loop;
- ``metric_readback`` the host-blocked portion of that readback (a tail
                     *inside* ``device_step``, reported separately, never
                     double-counted);
- ``checkpoint`` / ``validate``  the cadence-gated save / validation pass;
- ``residual``       ``wall − accounted`` — everything unattributed
                     (cadence bookkeeping, logging, lr-schedule eval).

Accounting identity (see docs/OBSERVABILITY.md for the full read-me):

    wall ≈ data_wait + stage_megabatch(inline) + dispatch + device_step
           + checkpoint + validate + residual

Strict with ``train_lookahead: 0`` / ``device_prefetch: 0`` (the
``scripts/obs_smoke.sh`` configuration asserts |residual| ≤ 5% of wall);
under lookahead/prefetch the device span overlaps later iterations' host
work by design, so ``residual`` can go negative and ``goodput`` is clamped.

Derived per record: ``samples_per_sec`` (host-local sequences/s over the
super-step) and ``goodput`` = device_step / wall ∈ (0, 1].

Everything here is host-side and stdlib-only; nothing may be called from
traced code (analysis rule ESR007).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Optional


class StepSpans:
    """One super-step's span bucket.

    Created by :meth:`StepAttribution.begin`, carried through the Trainer's
    ``pending`` deque alongside the in-flight metrics, finalized when both
    the loop body closed it (wall-clock end) AND the metrics readback
    resolved it (device span end) — whichever happens last emits.
    """

    __slots__ = (
        "first", "k", "t0", "t_close", "t_dispatch", "t_resolved",
        "spans", "overlapped", "readback_s", "emitted",
    )

    def __init__(self, t0: float):
        self.first: Optional[int] = None
        self.k: int = 0
        self.t0 = t0
        self.t_close: Optional[float] = None
        self.t_dispatch: Optional[float] = None
        self.t_resolved: Optional[float] = None
        self.spans: Dict[str, float] = {}
        self.overlapped: set = set()
        self.readback_s = 0.0
        self.emitted = False

    def add(self, name: str, seconds: float, overlapped: bool = False):
        self.spans[name] = self.spans.get(name, 0.0) + float(seconds)
        if overlapped:
            self.overlapped.add(name)


class StepAttribution:
    """Per-super-step wall-clock attribution driver (host-side).

    Every method is a no-op-safe cheap host operation: with no open bucket
    (or no sink) instrumented call sites cost a ``None`` check, so wrapped
    steps stay usable outside the training loop (tests, bench).
    """

    def __init__(
        self,
        sink=None,
        batch_size: int = 1,
        log_step: int = 1,
        clock=time.monotonic,
    ):
        self.sink = sink
        self.batch_size = max(int(batch_size), 1)
        self.log_step = max(int(log_step), 1)
        self._clock = clock
        self.current: Optional[StepSpans] = None
        self.emitted_records = 0

    # -- super-step lifecycle ---------------------------------------------

    def begin(self) -> StepSpans:
        """Open a fresh bucket at the top of a loop iteration."""
        self.current = StepSpans(self._clock())
        return self.current

    def discard(self) -> None:
        """Drop an empty bucket (source exhausted before a group arrived)."""
        self.current = None

    def note(self, first: int, k: int) -> None:
        """Record which iterations this super-step covers."""
        if self.current is not None:
            self.current.first = int(first)
            self.current.k = int(k)

    def close(self) -> None:
        """Mark the wall-clock end of the loop body; detaches the bucket
        (it lives on in the pending entry until the readback resolves it).
        Idempotent."""
        cur = self.current
        if cur is None:
            return
        if cur.t_close is None:
            cur.t_close = self._clock()
        self.current = None
        self._maybe_emit(cur)

    # -- span recording ----------------------------------------------------

    @contextmanager
    def measure(self, name: str):
        """Time a block into the current bucket (nested/overlapping blocks
        each record their full duration under their own name)."""
        cur = self.current
        if cur is None:
            yield
            return
        t0 = self._clock()
        try:
            yield
        finally:
            cur.add(name, self._clock() - t0)

    def add(self, name: str, seconds: float, overlapped: bool = False):
        if self.current is not None:
            self.current.add(name, seconds, overlapped=overlapped)

    def dispatched(self) -> None:
        """Timestamp the (async) dispatch of this super-step's device work."""
        if self.current is not None:
            self.current.t_dispatch = self._clock()

    @contextmanager
    def resolving(self, bucket: Optional[StepSpans]):
        """Wrap the cadence-gated scalar readback that forces the device
        sync: the block duration is the host-blocked ``metric_readback``;
        its end resolves the non-blocking ``device_step`` span."""
        if bucket is None:
            yield
            return
        t0 = self._clock()
        try:
            yield
        finally:
            now = self._clock()
            bucket.readback_s += now - t0
            bucket.t_resolved = now
            self._maybe_emit(bucket)

    # -- emission ----------------------------------------------------------

    def record(self, bucket: StepSpans) -> Dict:
        """The attribution record for a finalized bucket (field order is
        the published schema — docs/OBSERVABILITY.md)."""
        # wall is the loop-BODY's span (t_close); under lookahead the
        # readback lands later and device work overlaps the next
        # iterations by design — t_resolved never extends the wall
        if bucket.t_close is not None:
            end = bucket.t_close
        elif bucket.t_resolved is not None:
            end = bucket.t_resolved
        else:
            end = self._clock()
        wall = max(end - bucket.t0, 1e-9)
        device = 0.0
        if bucket.t_dispatch is not None and bucket.t_resolved is not None:
            device = max(bucket.t_resolved - bucket.t_dispatch, 0.0)
        spans = bucket.spans
        accounted = device + sum(
            v for n, v in spans.items() if n not in bucket.overlapped
        )
        k = bucket.k or 1
        return {
            "first_iteration": bucket.first,
            "k": k,
            "wall_s": round(wall, 6),
            "data_wait_s": round(spans.get("data_wait", 0.0), 6),
            "stage_megabatch_s": round(spans.get("stage_megabatch", 0.0), 6),
            "stage_overlapped": "stage_megabatch" in bucket.overlapped,
            "dispatch_s": round(spans.get("dispatch", 0.0), 6),
            "device_step_s": round(device, 6),
            "metric_readback_s": round(bucket.readback_s, 6),
            "checkpoint_s": round(spans.get("checkpoint", 0.0), 6),
            "validate_s": round(spans.get("validate", 0.0), 6),
            "residual_s": round(wall - accounted, 6),
            "samples_per_sec": round(k * self.batch_size / wall, 3),
            "goodput": round(min(max(device / wall, 1e-9), 1.0), 6),
        }

    def _due(self, bucket: StepSpans) -> bool:
        """Emission snaps to the ``train_log_step`` cadence exactly like
        the Trainer's loss line: due when ANY covered iteration hits it."""
        if bucket.first is None:
            return False
        return any(
            (bucket.first + j) % self.log_step == 0 for j in range(bucket.k)
        )

    def _maybe_emit(self, bucket: StepSpans) -> None:
        # a bucket emits once, after BOTH wall end and readback are known:
        # lookahead=0 resolves mid-body and emits at close; lookahead>0
        # closes first and emits at the deferred readback.
        if bucket.emitted:
            return
        if bucket.t_close is None or bucket.t_resolved is None:
            return
        bucket.emitted = True
        if not self._due(bucket):
            return
        rec = self.record(bucket)
        self.emitted_records += 1
        if self.sink is not None:
            self.sink.attribution(rec)
