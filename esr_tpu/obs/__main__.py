"""CLI: ``python -m esr_tpu.obs <export|report> ...``.

- ``export telemetry.jsonl [-o trace.json]`` — Chrome trace-event /
  Perfetto JSON (open in ``ui.perfetto.dev``; obs/export.py).
- ``report telemetry.jsonl [--slo configs/slo.yml] [-o report.json]`` —
  offline rollup (goodput, per-span p50/p99, per-class window latency,
  trace completeness) printed as JSON; with ``--slo`` the run is gated
  against declarative thresholds (obs/report.py).

Both subcommands take ``--run-index N`` to select a run of an appended
multi-run file (default ``-1`` = the last run; out-of-range exits 2).

Exit codes: 0 ok / every SLO rule passed, 1 SLO violation, 2 usage or
unreadable input (a broken gate must fail loudly, never pass silently).
Full walkthrough: docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m esr_tpu.obs",
        description=(
            "telemetry.jsonl tooling: Perfetto export + SLO-gated run "
            "reporter (docs/OBSERVABILITY.md)"
        ),
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    ex = sub.add_parser(
        "export", help="convert telemetry.jsonl to Perfetto/Chrome JSON"
    )
    ex.add_argument("telemetry", help="path to a telemetry.jsonl")
    ex.add_argument(
        "-o", "--out", default=None,
        help="output path (default: <telemetry>.trace.json)",
    )
    ex.add_argument(
        "--run-index", type=int, default=-1,
        help="which run of an appended multi-run file (0-based; negative "
             "counts from the end; default -1 = last run)",
    )

    rp = sub.add_parser(
        "report", help="roll up a run and (optionally) gate it on an SLO"
    )
    rp.add_argument("telemetry", help="path to a telemetry.jsonl")
    rp.add_argument(
        "--slo", default=None, metavar="YAML",
        help="SLO thresholds (e.g. configs/slo.yml); exit 1 on violation",
    )
    rp.add_argument(
        "-o", "--out", default=None,
        help="also write the JSON document to this path",
    )
    rp.add_argument(
        "--run-index", type=int, default=-1,
        help="which run of an appended multi-run file (0-based; negative "
             "counts from the end; default -1 = last run)",
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "export":
        from esr_tpu.obs.export import export_file

        out = args.out or (args.telemetry + ".trace.json")
        try:
            stats = export_file(args.telemetry, out,
                                run_index=args.run_index)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(json.dumps(stats))
        return 0

    from esr_tpu.obs.report import report_file

    try:
        doc, code = report_file(args.telemetry, args.slo, args.out,
                                run_index=args.run_index)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(json.dumps(doc, indent=2))
    return code


if __name__ == "__main__":
    sys.exit(main())
