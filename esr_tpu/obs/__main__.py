"""CLI: ``python -m esr_tpu.obs <export|report|drift> ...``.

- ``export telemetry.jsonl [more.jsonl ...] [-o trace.json]`` — Chrome
  trace-event / Perfetto JSON (open in ``ui.perfetto.dev``;
  obs/export.py). Several paths (a fleet's router + replica files,
  optionally ``label=path``) merge into one trace with per-replica
  process groups.
- ``report telemetry.jsonl [more.jsonl ...] [--slo configs/slo.yml]
  [-o report.json]`` — offline rollup (goodput, per-span p50/p99,
  per-class window latency, trace completeness, numerics) printed as
  JSON; with ``--slo`` the run is gated against declarative thresholds
  (obs/report.py). Several paths merge into one FLEET-level rollup
  (exact percentiles — merge==concat) with a per-replica ``replicas``
  section; the SLO gates the fleet view (docs/SERVING.md "The fleet").
- ``drift [--dtype bf16] [--break-tag TAG] [--fail-on-drift]`` — the
  precision-drift attribution harness (obs v4, obs/numerics.py): one
  seeded batch through an f32-reference and a candidate-dtype twin of
  the probed model, per-tag rel-error ladder naming the first layer
  exceeding tolerance. With ``--fail-on-drift`` an offender exits 1 —
  the CI shape of the precision-ladder gate (docs/PERF.md).

export/report take ``--run-index N`` to select a run of an appended
multi-run file (default ``-1`` = the last run; out-of-range exits 2).

Exit codes: 0 ok / every SLO rule passed, 1 SLO violation (or drift
offender under ``--fail-on-drift``), 2 usage or unreadable input (a
broken gate must fail loudly, never pass silently).
Full walkthrough: docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m esr_tpu.obs",
        description=(
            "telemetry.jsonl tooling: Perfetto export + SLO-gated run "
            "reporter (docs/OBSERVABILITY.md)"
        ),
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    ex = sub.add_parser(
        "export", help="convert telemetry.jsonl to Perfetto/Chrome JSON"
    )
    ex.add_argument(
        "telemetry", nargs="+",
        help="telemetry.jsonl path(s); several (optionally `label=path` "
             "— a fleet's router + replica files) merge into ONE trace "
             "with per-replica process groups",
    )
    ex.add_argument(
        "-o", "--out", default=None,
        help="output path (default: <telemetry>.trace.json)",
    )
    ex.add_argument(
        "--run-index", type=int, default=-1,
        help="which run of an appended multi-run file (0-based; negative "
             "counts from the end; default -1 = last run)",
    )

    rp = sub.add_parser(
        "report", help="roll up a run and (optionally) gate it on an SLO"
    )
    rp.add_argument(
        "telemetry", nargs="+",
        help="telemetry.jsonl path(s); several (optionally `label=path` "
             "— a fleet's router + replica files) merge into one "
             "fleet-level rollup with a per-replica `replicas` section",
    )
    rp.add_argument(
        "--slo", default=None, metavar="YAML",
        help="SLO thresholds (e.g. configs/slo.yml); exit 1 on violation",
    )
    rp.add_argument(
        "-o", "--out", default=None,
        help="also write the JSON document to this path",
    )
    rp.add_argument(
        "--run-index", type=int, default=-1,
        help="which run of an appended multi-run file (0-based; negative "
             "counts from the end; default -1 = last run)",
    )

    dr = sub.add_parser(
        "drift",
        help="precision-drift attribution: f32 vs candidate-dtype twin, "
             "per-layer rel-error ladder (docs/OBSERVABILITY.md)",
    )
    dr.add_argument(
        "--dtype", default="bfloat16",
        help="candidate dtype for the twin (default bfloat16; the "
             "config spellings bf16/f16/f32 are accepted too; int8 "
             "reruns the SAME f32 feed under the PTQ seam quantization "
             "and attributes per-layer quantization error)",
    )
    dr.add_argument("--basech", type=int, default=8,
                    help="model base channel count (default 8)")
    dr.add_argument("--hw", type=int, default=32,
                    help="square spatial size of the seeded batch")
    dr.add_argument("--frames", type=int, default=3,
                    help="window length / num_frame (default 3)")
    dr.add_argument("--batch", type=int, default=1)
    dr.add_argument("--seed", type=int, default=0)
    dr.add_argument(
        "--tolerance", type=float, default=0.25,
        help="per-tag rel-error threshold naming an offender "
             "(default 0.25 — well above honest bf16 layer noise, well "
             "below a genuinely broken layer)",
    )
    dr.add_argument(
        "--break-tag", default=None, metavar="TAG",
        help="arm the seeded precision-breaking fixture at this probe "
             "tag (the harness must then finger exactly it)",
    )
    dr.add_argument(
        "--fail-on-drift", action="store_true",
        help="exit 1 when any tag exceeds tolerance (CI gate shape)",
    )
    dr.add_argument(
        "-o", "--out", default=None,
        help="also write the JSON document to this path",
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "export":
        from esr_tpu.obs.export import export_file, export_files
        from esr_tpu.obs.report import split_label

        out = args.out or (split_label(args.telemetry[0])[1]
                           + ".trace.json")
        try:
            if len(args.telemetry) == 1 and "=" not in args.telemetry[0]:
                stats = export_file(args.telemetry[0], out,
                                    run_index=args.run_index)
            else:
                stats = export_files(args.telemetry, out,
                                     run_index=args.run_index)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(json.dumps(stats))
        return 0

    if args.cmd == "drift":
        from esr_tpu.obs.numerics import run_drift

        try:
            doc = run_drift(
                dtype=args.dtype, basech=args.basech, hw=args.hw,
                frames=args.frames, batch=args.batch, seed=args.seed,
                tolerance=args.tolerance, break_tag=args.break_tag,
            )
        except (TypeError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.out is not None:
            with open(args.out, "w") as f:
                json.dump(doc, f, indent=2)
        print(json.dumps(doc, indent=2))
        if args.fail_on_drift and doc["first_offender"] is not None:
            return 1
        return 0

    from esr_tpu.obs.report import report_files

    try:
        doc, code = report_files(args.telemetry, args.slo, args.out,
                                 run_index=args.run_index)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(json.dumps(doc, indent=2))
    return code


if __name__ == "__main__":
    sys.exit(main())
