"""telemetry.jsonl → Chrome trace-event / Perfetto JSON.

The sink's JSONL is the durable record; this module turns it into a
timeline a human can scrub in ``ui.perfetto.dev`` (File → Open, or drag
the exported ``.json`` next to a ``jax.profiler`` device trace from the
``trainer.profile`` hook — the two open side by side). Output is the
Chrome trace-event format (the JSON flavor Perfetto ingests natively):

- **one track per host thread** (process "host"): every span becomes a
  complete event (``ph: "X"``) on its emitting thread's track. v2 spans
  place by their ``begin``/``end`` fields; v1 spans (durations only) are
  placed ending at their record time ``t`` — same convention the sink's
  readers always assumed.
- **one virtual track per lane** (process "lanes"): spans/events carrying
  a ``lane`` field (``serve_admit``, ``serve_chunk_part``,
  ``serve_preempt``) draw each lane's occupancy timeline.
- **one virtual track per request class** (process "requests"):
  ``serve_request`` root spans + terminal events grouped by ``cls`` — the
  per-class SLO picture.
- **counter tracks** (``ph: "C"``): counters (running total) and gauges
  (sampled value) — queue depth, lane occupancy, prefetch stalls,
  backpressure.
- point events become instants (``ph: "i"``); trace linkage
  (``trace_id``/``span_id``/``parent_id``) rides in ``args`` so a slice
  click shows its family, and ``obs/report.py`` can check connectivity
  machine-side.

The reader (:func:`read_telemetry`) is the ONE ingestion point shared
with ``obs/report.py``: schema v1 and v2 files both normalize, and a
torn final line (a SIGKILLed run — the sink flushes per record, so at
most one line can be mid-write) is tolerated, not fatal.

stdlib-only, like the whole obs package (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "read_telemetry",
    "to_chrome_trace",
    "export_file",
    "span_index",
    "trace_of",
]

# fixed virtual-process ids for the exported track groups
_PID_HOST = 1
_PID_LANES = 2
_PID_REQUESTS = 3
_PID_COUNTERS = 4


def read_telemetry(
    path: str, run_index: int = -1
) -> Tuple[Optional[Dict], List[Dict], int]:
    """Parse one telemetry.jsonl → ``(manifest, records, torn_lines)``.

    - the manifest is the run's ``type: "manifest"`` header record (None
      for a file that lost its header — still readable);
    - **appended multi-run files return ONE run** (``run_index``, default
      ``-1`` = the last — today's pinned behavior): the sink opens its
      file in append mode, and every run's ``t``/``begin`` axis restarts
      at zero — merging two runs would overlay their timelines (inflating
      the reporter's serving wall and drawing two runs on top of each
      other in Perfetto). Each manifest record starts a fresh segment;
      ``run_index`` selects among them (negative indices count from the
      end, list-style), and an out-of-range index raises ``ValueError``
      naming how many runs the file holds — plumbed through
      ``obs export --run-index`` / ``obs report --run-index`` so earlier
      runs stay reachable;
    - v1 files (``schema_version: 1``, spans without trace fields) come
      back as-is; consumers treat missing trace fields as "unlinked";
    - unparseable lines are skipped and counted (``torn_lines``): a
      SIGKILL mid-write tears at most the final line because every record
      is flushed as it is written (obs/sink.py).
    """
    # Streaming with bounded retention: only segments still REACHABLE by
    # the requested index keep their parsed records (the last |run_index|
    # for a negative index — one for the default -1, matching the old
    # last-run-wins memory profile on arbitrarily long appended files;
    # exactly the target segment for a non-negative index). Every other
    # segment is parsed only enough to be counted.
    keep_last = None if run_index >= 0 else -run_index
    # (ordinal, manifest, records, torn) for retained segments only
    segments: List[Tuple[int, Optional[Dict], List[Dict], int]] = []
    ordinal = -1  # index of the open segment; -1 = none opened yet
    manifest: Optional[Dict] = None
    records: List[Dict] = []
    torn = 0

    def _keep(idx: int) -> bool:
        return keep_last is not None or idx == run_index

    def _close_open() -> None:
        if ordinal < 0:
            return
        if _keep(ordinal):
            segments.append((ordinal, manifest, records, torn))
            if keep_last is not None and len(segments) > keep_last:
                segments.pop(0)

    # errors="replace": a SIGKILL can tear the final line mid-multibyte
    # character; strict decoding would raise UnicodeDecodeError before
    # json.loads ever ran, breaking the crash-safe contract — replacement
    # chars make the torn line fail JSON parsing and count as torn
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                rec = None
            if rec is not None and (
                not isinstance(rec, dict) or "type" not in rec
            ):
                rec = None
            if rec is not None and rec["type"] == "manifest":
                # a new run appended to the same file: close the previous
                # segment (headerless leading records form their own)
                _close_open()
                ordinal += 1
                manifest, records, torn = rec, [], 0
                continue
            if ordinal < 0:
                ordinal = 0  # headerless leading lines open segment 0
                manifest, records, torn = None, [], 0
            if rec is None:
                torn += 1
            elif _keep(ordinal):
                records.append(rec)
    _close_open()
    total = ordinal + 1
    if total == 0:
        return None, [], 0  # empty file: the pinned pre-multi-run shape
    actual = run_index if run_index >= 0 else total + run_index
    if not 0 <= actual < total:
        raise ValueError(
            f"run_index {run_index} out of range: {path!r} holds "
            f"{total} run(s)"
        )
    for idx, man, recs, torn_n in segments:
        if idx == actual:
            return man, recs, torn_n
    raise AssertionError("retained segment lookup cannot miss")


def span_index(records: Iterable[Dict]) -> Dict[str, Dict]:
    """``{span_id: span record}`` over every identified span."""
    out: Dict[str, Dict] = {}
    for rec in records:
        if rec.get("type") == "span" and rec.get("span_id"):
            out[rec["span_id"]] = rec
    return out


def trace_of(records: Iterable[Dict], trace_id: str) -> List[Dict]:
    """Every record belonging to one trace, in file order."""
    return [r for r in records if r.get("trace_id") == trace_id]


def _span_edges(rec: Dict) -> Tuple[float, float]:
    """(begin, end) seconds on the sink's ``t`` axis. v2 spans carry the
    edges; v1 spans end at their record time ``t``."""
    seconds = float(rec.get("seconds", 0.0) or 0.0)
    if rec.get("begin") is not None and rec.get("end") is not None:
        return float(rec["begin"]), float(rec["end"])
    t = float(rec.get("t", 0.0))
    return t - seconds, t


def _args_of(rec: Dict) -> Dict:
    skip = {"t", "type", "name", "seconds", "begin", "end", "thread"}
    return {k: v for k, v in rec.items() if k not in skip}


class _Tids:
    """Stable small integer tids per track label within one process."""

    def __init__(self):
        self._by_label: Dict[object, int] = {}

    def get(self, label) -> int:
        if label not in self._by_label:
            self._by_label[label] = len(self._by_label)
        return self._by_label[label]

    def items(self):
        return self._by_label.items()


def to_chrome_trace(
    records: Iterable[Dict], manifest: Optional[Dict] = None
) -> Dict:
    """Normalized telemetry records → a Chrome trace-event JSON object
    (``{"traceEvents": [...], ...}``) loadable in ``ui.perfetto.dev``."""
    events: List[Dict] = []
    host_tids = _Tids()
    lane_tids = _Tids()
    class_tids = _Tids()
    host_tids.get("main")  # tid 0 is always the main host track

    def _track(rec: Dict) -> Tuple[int, int]:
        # serve_admit spans cover submit -> bind (mostly QUEUE wait):
        # drawing them on the lane track would paint the lane occupied
        # for the whole wait, overlapping the chunks it actually served
        # — they belong to the request-class story, like the roots
        if rec.get("name") == "serve_request" or (
            rec.get("name") == "serve_admit" and rec.get("cls") is not None
        ) or (
            rec.get("type") == "event" and rec.get("request") is not None
            and rec.get("lane") is None
        ):
            return _PID_REQUESTS, class_tids.get(rec.get("cls", "default"))
        if rec.get("lane") is not None:
            return _PID_LANES, lane_tids.get(int(rec["lane"]))
        return _PID_HOST, host_tids.get(rec.get("thread", "main"))

    for rec in records:
        kind = rec.get("type")
        if kind == "span":
            begin, end = _span_edges(rec)
            pid, tid = _track(rec)
            events.append({
                "ph": "X",
                "name": rec.get("name", "span"),
                "pid": pid,
                "tid": tid,
                "ts": round(begin * 1e6, 3),
                "dur": round(max(end - begin, 0.0) * 1e6, 3),
                "cat": "span",
                "args": _args_of(rec),
            })
        elif kind in ("counter", "gauge"):
            value = rec.get("total") if kind == "counter" else rec.get("value")
            try:
                value = float(value)
            except (TypeError, ValueError):
                continue
            events.append({
                "ph": "C",
                "name": rec.get("name", kind),
                "pid": _PID_COUNTERS,
                "tid": 0,
                "ts": round(float(rec.get("t", 0.0)) * 1e6, 3),
                "args": {"value": value},
            })
        elif kind == "event":
            pid, tid = _track(rec)
            events.append({
                "ph": "i",
                "s": "t",
                "name": rec.get("name", "event"),
                "pid": pid,
                "tid": tid,
                "ts": round(float(rec.get("t", 0.0)) * 1e6, 3),
                "cat": "event",
                "args": _args_of(rec),
            })
        elif kind == "attribution":
            # the span tree for a super-step is emitted alongside the
            # attribution record (obs/spans.py); the record itself would
            # only duplicate those slices
            continue

    meta: List[Dict] = []

    def _name(pid: int, name: str, sort: int) -> None:
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "args": {"name": name}})
        meta.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                     "args": {"sort_index": sort}})

    _name(_PID_HOST, "host", 0)
    _name(_PID_LANES, "lanes", 1)
    _name(_PID_REQUESTS, "requests", 2)
    _name(_PID_COUNTERS, "counters", 3)
    for label, tid in host_tids.items():
        meta.append({"ph": "M", "name": "thread_name", "pid": _PID_HOST,
                     "tid": tid, "args": {"name": str(label)}})
    for label, tid in lane_tids.items():
        meta.append({"ph": "M", "name": "thread_name", "pid": _PID_LANES,
                     "tid": tid, "args": {"name": f"lane {label}"}})
    for label, tid in class_tids.items():
        meta.append({"ph": "M", "name": "thread_name", "pid": _PID_REQUESTS,
                     "tid": tid, "args": {"name": f"class {label}"}})

    out = {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
    }
    if manifest is not None:
        out["metadata"] = {
            k: manifest.get(k)
            for k in ("host", "pid", "jax_version", "device_kind",
                      "platform", "schema_version", "config_fingerprint")
            if k in manifest
        }
    return out


def export_file(in_path: str, out_path: str, run_index: int = -1) -> Dict:
    """Read a telemetry.jsonl and write the Perfetto-loadable JSON;
    returns ``{"events": n, "torn_lines": n, "out": path}``.
    ``run_index`` selects a run of an appended multi-run file
    (:func:`read_telemetry`)."""
    manifest, records, torn = read_telemetry(in_path, run_index=run_index)
    doc = to_chrome_trace(records, manifest)
    with open(out_path, "w") as f:
        json.dump(doc, f)
    return {
        "events": len(doc["traceEvents"]),
        "records": len(records),
        "torn_lines": torn,
        "out": out_path,
    }


# pid stride between replica process groups in a merged fleet export:
# each file keeps its host/lanes/requests/counters track split, shifted
# into its own block and labeled "<replica>: <track>"
_FLEET_PID_STRIDE = 10


def export_files(
    in_args, out_path: str, run_index: int = -1
) -> Dict:
    """Merge N telemetry files (a fleet: router + one per replica) into
    ONE Perfetto trace: each file's tracks land in their own pid block,
    process names prefixed with the replica label
    (``obs.report.split_label`` — ``r0=path`` or filename-derived), so
    lanes/requests/counters of different replicas never overlay. Each
    file keeps its own zero-based time axis — replicas start together in
    a fleet run, so tracks align to within startup skew (the same reason
    appended RUNS of one file are still selected, never merged)."""
    from esr_tpu.obs.report import split_label

    events = []
    total_records = 0
    total_torn = 0
    for i, arg in enumerate(in_args):
        label, path = split_label(arg)
        manifest, records, torn = read_telemetry(path, run_index=run_index)
        total_records += len(records)
        total_torn += torn
        doc = to_chrome_trace(records, manifest)
        offset = i * _FLEET_PID_STRIDE
        for ev in doc["traceEvents"]:
            ev = dict(ev)
            if "pid" in ev:
                ev["pid"] = int(ev["pid"]) + offset
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                ev["args"] = {
                    "name": f"{label}: {ev['args'].get('name', '')}"
                }
            events.append(ev)
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(out_path, "w") as f:
        json.dump(out, f)
    return {
        "events": len(events),
        "records": total_records,
        "torn_lines": total_torn,
        "files": len(list(in_args)),
        "out": out_path,
    }
