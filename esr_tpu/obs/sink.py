"""Structured JSONL telemetry sink: events, counters, gauges, metrics, spans.

The single place host-side telemetry lands (docs/OBSERVABILITY.md).
``MetricWriter`` (utils/writer.py), ``MetricTracker``/``YamlLogger``
(utils/trackers.py), the ``DevicePrefetcher`` health channel
(data/loader.py), the ``checked_jit`` compile events
(analysis/retrace_guard.py), and the Trainer's per-super-step attribution
records (obs/spans.py) all write through one :class:`TelemetrySink`.

Contract:

- **stdlib-only.** The sink is importable from the NumPy-only data layer
  (ESR004) and from CI hosts with no accelerator runtime. ``jax`` is only
  touched lazily, inside :func:`run_manifest`, and NEVER in a way that can
  initialize a backend (the manifest probe must stay safe inside
  wedge-proof artifact paths like ``bench.py``/``tpu_probe``).
- **host-side only.** Nothing in this package may be called from
  jitted/scanned code — a sink call under trace either leaks a tracer or
  fires exactly once at trace time. Enforced statically by analysis rule
  ESR007 and ``tests/test_obs.py``'s repo-wide self-check.
- **monotonic clock.** Every record carries ``t`` — seconds since the sink
  opened, from ``time.monotonic`` — so ordering and durations are immune to
  wall-clock steps; wall-clock appears only in the manifest (``ts``).
- **never raises into the hot loop.** I/O failures drop the record and
  count it (``sink.dropped``); telemetry must not take training down.
- **stable key order.** Records of the same type emit keys in a
  deterministic order (fixed ``t``/``type``/``name`` prefix, payload keys
  sorted) so downstream parsers and diffs are stable; attribution records
  keep their curated field order (obs/spans.py).
"""

from __future__ import annotations

import atexit
import hashlib
import json
import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, Optional, Tuple

# module-level, not per-record: trace.py never imports sink at module
# scope (its sink lookup is lazy inside SpanHandle.end), so this creates
# no cycle — and _trace_fields runs on EVERY record write
from esr_tpu.obs.trace import current as _trace_current

logger = logging.getLogger(__name__)

# v2 (docs/OBSERVABILITY.md "Schema v2"): span records MAY carry trace
# context (trace_id / span_id / parent_id), begin/end timestamps on the
# sink clock base, and a host thread name; events/counters/gauges MAY
# carry trace_id/parent_id. v1 files (none of those fields) stay readable
# — obs/export.read_telemetry normalizes both.
SCHEMA_VERSION = 2


def config_fingerprint(config: Dict) -> str:
    """Stable 16-hex digest of an effective run config (order-insensitive:
    canonical JSON with sorted keys; non-JSON leaves stringified)."""
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _jax_version() -> Optional[str]:
    try:
        import jax

        return jax.__version__
    except Exception:  # noqa: BLE001 - jax-free hosts still get a manifest
        return None


def _device_info() -> Dict:
    """Device kind/platform/count — ONLY if a backend is already live.

    ``jax.devices()`` initializes (and can wedge on) the backend; the
    manifest is stamped into wedge-proof artifact paths, so probe the
    initialized-backends flag first and report nulls otherwise. Callers
    that run after backend contact (Trainer, bench stages past
    ``backend_up``) get real values.
    """
    info: Dict = {"device_kind": None, "platform": None, "device_count": None}
    try:
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return info
        import jax

        devs = jax.devices()
        info["device_kind"] = devs[0].device_kind
        info["platform"] = devs[0].platform
        info["device_count"] = len(devs)
    except Exception:  # noqa: BLE001 - best-effort; nulls are valid
        pass
    return info


_STATIC_MANIFEST: Optional[Dict] = None


def run_manifest(config_fingerprint: Optional[str] = None) -> Dict:
    """The per-run environment manifest: host, pid, python, jax version,
    device kind (when a backend is live), optional config fingerprint.

    Static fields are computed once per process; the device fields are
    re-probed each call until a backend exists (so records emitted after
    backend contact pick up the real device kind)."""
    global _STATIC_MANIFEST
    if _STATIC_MANIFEST is None:
        import platform

        _STATIC_MANIFEST = {
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "python": platform.python_version(),
            "jax_version": _jax_version(),
        }
    man = dict(_STATIC_MANIFEST)
    man.update(_device_info())
    if config_fingerprint is not None:
        man["config_fingerprint"] = config_fingerprint
    return man


class TelemetrySink:
    """Append-only JSONL event/metric sink with a manifest header record.

    Thread-safe (the ``DevicePrefetcher`` producer thread and the training
    loop write concurrently); every record is flushed the moment it exists,
    matching the wedge-proof contract of ``utils/artifacts.emit_jsonl``.
    """

    def __init__(
        self,
        path: str,
        manifest: Optional[Dict] = None,
        clock=time.monotonic,
    ):
        self.path = path
        self._clock = clock
        self._t0 = clock()
        # trace begin/end timestamps arrive as raw time.monotonic values
        # (obs/trace.py); rel() maps them onto the same zero as `t`. Kept
        # separate from _t0 so injected test clocks don't skew it.
        self._mono0 = time.monotonic()
        self._lock = threading.RLock()
        self._counts: Dict[str, float] = {}
        self.dropped = 0
        # record observers (obs v3, docs/OBSERVABILITY.md "live plane"):
        # each is called with every record dict right after it is built —
        # the LiveAggregator's tap. Copy-on-write tuple so the hot write
        # path iterates without taking the lock; observer exceptions are
        # counted + warned once, never raised into the emitting loop.
        self._observers: Tuple[Callable[[Dict], None], ...] = ()
        self.observer_errors = 0
        self._observer_warned = False
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a")
        man = dict(manifest if manifest is not None else run_manifest())
        man["schema_version"] = SCHEMA_VERSION
        man["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        self._write("manifest", "run", man)
        # crash-safe teardown: every record is already flushed on write, so
        # a SIGKILL leaves at worst one torn final line (tolerated by the
        # v1/v2 reader); atexit covers the softer exits — an interpreter
        # shutting down with the sink still open closes the file cleanly
        # instead of relying on GC order (docs/OBSERVABILITY.md).
        self._atexit = self.close
        atexit.register(self._atexit)

    # -- record plumbing ---------------------------------------------------

    def _write(self, type_: str, name: str, fields: Dict, sort: bool = True):
        rec = {
            "t": round(self._clock() - self._t0, 6),
            "type": type_,
            "name": name,
        }
        for k, v in sorted(fields.items()) if sort else fields.items():
            rec[k] = v
        try:
            line = json.dumps(rec)
        except (TypeError, ValueError):
            rec = {**{k: rec[k] for k in ("t", "type", "name")},
                   "unserializable": True}
            line = json.dumps(rec)
        written = False
        with self._lock:
            if self._f is None or self._f.closed:
                self.dropped += 1
            else:
                try:
                    # the file IS the resource the lock serializes, and
                    # flush-per-record is the crash-safety contract — a
                    # local append+flush is a bounded syscall, not an
                    # unbounded wait (docs/OBSERVABILITY.md)
                    self._f.write(line + "\n")  # esr: noqa(CX003)
                    self._f.flush()  # esr: noqa(CX003)
                    written = True
                except (OSError, ValueError):
                    self.dropped += 1
        # observers see EXACTLY the records that landed in the JSONL
        # (including the unserializable fallback) — a dropped record
        # (closed sink, full disk) must not advance the live view, or
        # live and offline rollups silently diverge
        if written:
            for observer in self._observers:
                try:
                    observer(rec)
                except Exception:  # noqa: BLE001 - live must not kill I/O
                    self.observer_errors += 1
                    if not self._observer_warned:
                        self._observer_warned = True
                        logger.warning(
                            "telemetry observer %r raised; counting "
                            "further failures silently "
                            "(sink.observer_errors)", observer,
                        )

    # -- record observers (obs v3 live plane) ------------------------------

    def add_observer(self, fn: Callable[[Dict], None]) -> None:
        """Register ``fn`` to receive every record dict this sink writes
        (called on the emitting thread, after the record is built and
        before the file write). The live plane's tap
        (``obs.aggregate.LiveAggregator.attach``)."""
        with self._lock:
            if fn not in self._observers:
                self._observers = self._observers + (fn,)

    def remove_observer(self, fn: Callable[[Dict], None]) -> None:
        with self._lock:
            self._observers = tuple(o for o in self._observers if o != fn)

    # -- v2 trace plumbing -------------------------------------------------

    def rel(self, monotonic_t: float) -> float:
        """Map a raw ``time.monotonic()`` stamp onto this sink's ``t``
        axis (seconds since the sink opened) — the clock base for span
        ``begin``/``end`` fields (obs/trace.py)."""
        return monotonic_t - self._mono0

    @staticmethod
    def _trace_fields(fields: Dict) -> Dict:
        """Attach the ambient trace context (obs/trace.py) when the caller
        did not link explicitly — this is what makes nested spans, compile
        events, and stall counters auto-join the enclosing trace without
        their call sites knowing about tracing."""
        if "trace_id" in fields:
            return fields
        ctx = _trace_current()
        if ctx is None:
            return fields
        out = dict(fields)
        out["trace_id"] = ctx.trace_id
        out.setdefault("parent_id", ctx.span_id)
        return out

    # -- record kinds ------------------------------------------------------

    def event(self, name: str, **fields) -> None:
        """A point-in-time occurrence (``compile``, ``prefetch_close``, …).
        v2: carries the emitting host thread like spans do, so the
        exporter draws instants on the track they causally belong to."""
        fields.setdefault("thread", threading.current_thread().name)
        self._write("event", name, self._trace_fields(fields))

    def counter(self, name: str, inc: float = 1, **fields) -> None:
        """A monotonically accumulating count; each record carries this
        increment and the running total."""
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + inc
            total = self._counts[name]
        self._write(
            "counter", name,
            self._trace_fields({"inc": inc, "total": total, **fields}),
        )

    def gauge(self, name: str, value, **fields) -> None:
        """A sampled instantaneous value (queue depth, lookahead fill)."""
        self._write("gauge", name,
                    self._trace_fields({"value": value, **fields}))

    def metric(self, name: str, value: float, step=None, **fields) -> None:
        """A training metric scalar (the MetricWriter/MetricTracker path)."""
        self._write("metric", name, {"value": float(value), "step": step,
                                     **fields})

    def span(self, name: str, seconds: float, **fields) -> None:
        """A completed named duration. v2: carries the host thread name
        (one exporter track per thread) and — explicitly from obs/trace.py
        or implicitly from the ambient context — its trace linkage."""
        payload = {"seconds": round(float(seconds), 6), **fields}
        payload.setdefault("thread", threading.current_thread().name)
        self._write("span", name, self._trace_fields(payload))

    def numerics(self, tag: str, stats: Dict, step=None, **fields) -> None:
        """One probe tag's merged tensor statistics at the cadence-gated
        readback (obs v4, docs/OBSERVABILITY.md "The numerics plane").
        ``tag`` comes from the static probe catalog
        (``esr_tpu.obs.numerics.TAG_ORDER``) — a bounded vocabulary, like
        span family names (ESR013); ``stats`` is the
        ``obs.numerics.stats_fields`` payload (rms, max_abs, mean,
        nonfinite, underflow, overflow, count, finite_frac)."""
        self._write(
            "numerics", tag,
            self._trace_fields({"step": step, **stats, **fields}),
        )

    def attribution(self, fields: Dict) -> None:
        """A per-super-step wall-clock attribution record (obs/spans.py);
        field order is curated by the producer and preserved."""
        self._write("attribution", "super_step", fields, sort=False)

    def counter_total(self, name: str) -> float:
        with self._lock:
            return self._counts.get(name, 0)

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            if self._f is not None and not self._f.closed:
                try:
                    # bounded local flush; the lock exists to exclude
                    # concurrent writers during teardown (see _write)
                    self._f.flush()  # esr: noqa(CX003)
                except (OSError, ValueError):
                    pass
                self._f.close()
            cb, self._atexit = getattr(self, "_atexit", None), None
        if cb is not None:
            try:
                atexit.unregister(cb)
            except Exception:  # noqa: BLE001 - interpreter teardown
                pass

    def __enter__(self) -> "TelemetrySink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# process-active sink: the one registry every instrumented component checks.
# None (the default) makes every telemetry call site a no-op — telemetry is
# strictly opt-in per process (the Trainer activates it on the main host).

_ACTIVE: Optional[TelemetrySink] = None


def set_active_sink(sink: Optional[TelemetrySink]) -> Optional[TelemetrySink]:
    """Install ``sink`` as the process-active sink; returns the previous
    one (restore it to scope activation, e.g. in tests)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = sink
    return prev


def active_sink() -> Optional[TelemetrySink]:
    return _ACTIVE
