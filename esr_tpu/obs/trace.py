"""Ambient trace context: every span gets an identity and a family tree.

PR 3's sink records flat, durations-only spans — ``name`` + ``seconds``,
no IDs, no begin/end, no parentage — so a serving request's journey
through admit → lane bind → N chunks → evict/resume → done, or a
super-step's attribution buckets, cannot be reconstructed as a causal
trace. This module is the v2 fix (docs/OBSERVABILITY.md "Schema v2"):

- **identity**: spans carry ``trace_id``/``span_id``/``parent_id`` (16-hex
  from ``os.urandom`` — no wall-clock or global RNG involved) plus
  ``begin``/``end`` monotonic timestamps on the *sink's* clock base
  (:meth:`esr_tpu.obs.sink.TelemetrySink.rel`), so a downstream reader can
  nest children inside parents and order siblings without trusting record
  order.
- **ambient propagation**: the current ``(trace_id, span_id)`` rides a
  ``contextvars.ContextVar``. Opening a span re-points the ambient context
  at itself, so *any* record emitted inside it — a nested span, a
  ``compile`` event from ``checked_jit``, a ``prefetch_stall`` counter —
  auto-links as a child without its call site knowing about tracing at all
  (the sink attaches the ambient context; see ``sink._trace_fields``).
- **cross-thread linking**: ``contextvars`` do NOT flow into worker
  threads on their own. A component that hands work to a thread captures
  the submitter's context (:func:`capture`) and the worker adopts it
  (:func:`adopt`) — the ``DevicePrefetcher`` producer and the
  async-checkpoint writer do exactly this, so their spans stop parking
  outside the causal tree.

Two entry styles:

- ``with trace.span("name", field=...):`` — the default; the span closes
  on every exit path.
- ``handle = trace.begin("name"); ...; handle.end()`` — for host loops
  whose begin and end live in different lexical blocks (the Trainer's
  run-level span). A manual ``begin()`` whose ``end()`` is not guaranteed
  on exception paths leaks the ambient context into everything emitted
  afterwards — analysis rule ESR010 (docs/ANALYSIS.md) polices this:
  ``end()`` must sit in a ``finally``.

Everything here is stdlib-only and host-side only (analysis rule ESR007),
like the rest of ``esr_tpu.obs``. With no active sink every operation
degrades to cheap bookkeeping — spans are safe to leave in library code.
"""

from __future__ import annotations

import contextvars
import os
import time
from contextlib import contextmanager
from typing import NamedTuple, Optional


class TraceContext(NamedTuple):
    """The ambient position in the trace tree: records emitted under this
    context belong to ``trace_id`` with parent ``span_id``."""

    trace_id: str
    span_id: str


_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "esr_tpu_obs_trace", default=None
)


def new_id() -> str:
    """A fresh 16-hex span/trace id (``os.urandom`` — unique across
    processes and threads, deterministic-clock-free)."""
    return os.urandom(8).hex()


def current() -> Optional[TraceContext]:
    """The ambient trace context of this thread/task, or None."""
    return _CTX.get()


def capture() -> Optional[TraceContext]:
    """Snapshot the ambient context for hand-off to a worker thread
    (alias of :func:`current`, named for intent at call sites)."""
    return _CTX.get()


@contextmanager
def adopt(ctx: Optional[TraceContext]):
    """Run a block under a captured context (worker-thread half of the
    cross-thread link). ``adopt(None)`` is a no-op, so producers created
    outside any trace cost nothing."""
    if ctx is None:
        yield
        return
    token = _CTX.set(ctx)
    try:
        yield
    finally:
        _CTX.reset(token)


# span-record keys the trace machinery owns; a payload field with one of
# these names is emitted with a trailing underscore instead of crashing
# end() (which runs in finallys) with a duplicate-kwarg TypeError
_RESERVED_FIELDS = frozenset(
    ("name", "seconds", "trace_id", "span_id", "parent_id", "begin", "end")
)


class SpanHandle:
    """One open span: identity + begin timestamp + the ambient token.

    Created by :func:`begin`/:func:`span`; emitted by :meth:`end`.
    ``end()`` is idempotent and never raises — it must be safe in the
    ``finally`` of a crashing loop. Payload fields colliding with the
    reserved span keys (``name``/``seconds``/``trace_id``/``span_id``/
    ``parent_id``/``begin``/``end``) emit as ``<key>_``.
    """

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "fields", "_sink", "_t0", "_token", "_ended",
    )

    def __init__(self, name: str, sink=None, **fields):
        parent = _CTX.get()
        self.name = name
        self.trace_id = parent.trace_id if parent else new_id()
        self.parent_id = parent.span_id if parent else None
        self.span_id = new_id()
        self.fields = dict(fields)
        self._sink = sink
        self._t0 = time.monotonic()
        self._token = _CTX.set(TraceContext(self.trace_id, self.span_id))
        self._ended = False

    @property
    def ctx(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def note(self, **fields) -> None:
        """Attach/override payload fields before the span closes."""
        self.fields.update(fields)

    def end(self, **fields) -> None:
        """Close the span: restore the parent ambient context and emit one
        v2 span record to the explicit (or process-active) sink."""
        if self._ended:
            return
        self._ended = True
        t1 = time.monotonic()
        try:
            _CTX.reset(self._token)
        except ValueError:
            # end() on a different thread/context than begin(): the token
            # is unusable there. Leave the ending thread's ambient context
            # ALONE — it belongs to whatever that thread is running under
            # (e.g. an adopt() block), and re-pointing it at this handle's
            # parent would mis-parent every record the thread emits next.
            # The begin thread's context dies with its thread/scope.
            pass
        if fields:
            self.fields.update(fields)
        sink = self._sink
        if sink is None:
            from esr_tpu.obs.sink import active_sink

            sink = active_sink()
        if sink is None:
            return
        payload = {
            (k + "_" if k in _RESERVED_FIELDS else k): v
            for k, v in self.fields.items()
        }
        sink.span(
            self.name,
            t1 - self._t0,
            trace_id=self.trace_id,
            span_id=self.span_id,
            parent_id=self.parent_id,
            begin=round(sink.rel(self._t0), 6),
            end=round(sink.rel(t1), 6),
            **payload,
        )


def begin(name: str, sink=None, **fields) -> SpanHandle:
    """Open a span MANUALLY (non-``with`` host-loop form). The caller owns
    the matching :meth:`SpanHandle.end` — put it in a ``finally`` or
    analysis rule ESR010 will flag the leak."""
    return SpanHandle(name, sink=sink, **fields)


@contextmanager
def span(name: str, sink=None, **fields):
    """Open a span for a ``with`` block — closes on every exit path.

    Yields the :class:`SpanHandle` so the block can ``note(...)`` extra
    payload resolved mid-flight."""
    handle = SpanHandle(name, sink=sink, **fields)
    try:
        yield handle
    finally:
        handle.end()


__all__ = [
    "TraceContext",
    "SpanHandle",
    "adopt",
    "begin",
    "capture",
    "current",
    "new_id",
    "span",
]
