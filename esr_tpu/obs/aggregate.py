"""In-process live rollups over the telemetry record stream (obs v3).

The offline reporter (obs/report.py) can only speak once the run is over;
the fleet direction in ROADMAP.md — an autoscaler and router driven by
queue depth and per-class p99 — needs the SAME rollup namespace while the
run is in flight. :class:`LiveAggregator` is that live half: it registers
as a record OBSERVER on the active :class:`~esr_tpu.obs.sink.TelemetrySink`
(``sink.add_observer`` — the tap fires once per record, right after the
record dict is built, so live and JSONL views are the same stream) and
maintains lock-cheap streaming state:

- **counters** — running totals from ``inc`` (summing increments instead
  of trusting ``total`` keeps per-window deltas exact);
- **gauges** — last value per name;
- **span sketches** — one mergeable log-bucketed quantile sketch
  (:class:`QuantileSketch`, DDSketch-style, fixed relative error,
  stdlib-only) per span family (``serve_chunk_part``, ``super_step``
  children, ``infer_chunk``, …), plus per-request-class window-latency
  sketches weighted by ``windows`` — the same expansion the offline
  reporter applies;
- **goodput / serving / traces** — the report-shaped aggregates the
  shipped ``configs/slo.yml`` rules dot into (``goodput.value``,
  ``serving.errors``, ``traces.incomplete``, …).

:meth:`LiveAggregator.snapshot` returns the offline reporter's dotted
namespace, so ``obs.report.evaluate_slo`` gates a LIVE snapshot with the
same YAML it gates a finished file — that is what ``obs/http.py``'s
``/slo`` endpoint does, multi-window.

**Windows.** Records additionally land in a ring of fixed-length epoch
states (``epoch_s`` seconds each, ``max_epochs`` bound). Because sketches
are mergeable (``merge == concat``, pinned in tests), a windowed rollup is
just the merge of the epochs covering the window — `snapshot(window_s=60)`
is the last-minute view the burn-rate evaluation compares against the
5-minute one. Epoch granularity is deliberately coarse: a window includes
every epoch that overlaps it.

Accuracy contract (pinned by ``tests/test_obs_live.py``): on identical
telemetry, live p50/p99 per span family agree with ``obs report``'s exact
interpolated percentiles within ``rel_err`` (both rank endpoints are
estimated within ``rel_err``, and the interpolation is the same convex
combination), and counters/counts match exactly.

**The wire format (obs v5, docs/OBSERVABILITY.md "The fleet view").**
A snapshot is also serializable: :meth:`LiveAggregator.snapshot_wire`
emits a versioned, JSON-safe document carrying the MERGED accumulation
state itself (sketch buckets, counters, gauges, numerics table) rather
than the rendered rollup, so a remote consumer
(:class:`esr_tpu.obs.fleetview.FleetAggregator`) can parse it with
:func:`parse_snapshot_wire` and keep merging — serialize → parse →
merge is bucket-for-bucket identical to an in-process merge, which is
what preserves the ``rel_err`` guarantee across the wire. A version or
``rel_err`` mismatch is rejected loudly (``ValueError``), never merged.

Everything here is stdlib-only and host-side only, like the rest of
``esr_tpu.obs`` (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, Iterable, Optional

# the reporter's conventions, shared (not copied): live snapshot values
# must match offline report values to formatting, and the router-level
# status taxonomy (continued / rootless terminals) must classify
# identically live and offline — docs/RESILIENCE.md
from esr_tpu.obs.report import (
    _CONTINUED_STATUSES,
    _ROOTLESS_STATUSES,
    _round,
)

# the numerics plane's per-tag accumulation + section rendering is ONE
# implementation shared with the offline reporter (obs/numerics.py) —
# the live/offline parity contract extended to value telemetry
from esr_tpu.obs import numerics as _numerics

__all__ = [
    "QuantileSketch",
    "LiveAggregator",
    "SNAPSHOT_WIRE_VERSION",
    "state_to_wire",
    "state_from_wire",
    "render_state",
    "parse_snapshot_wire",
]

# the snapshot wire schema (obs v5): bumped on any change to the state
# document shape; a parser seeing an unknown version must refuse to
# merge (a silently-misparsed remote snapshot would corrupt the fleet
# rollup without any visible failure)
SNAPSHOT_WIRE_VERSION = 1


class QuantileSketch:
    """Mergeable log-bucketed quantile sketch (DDSketch-style).

    Values are counted in geometric buckets ``(gamma^(k-1), gamma^k]``
    with ``gamma = (1 + rel_err) / (1 - rel_err)``; every bucket's
    representative value ``2 * gamma^k / (gamma + 1)`` is within
    ``rel_err`` (relative) of every value the bucket holds, so any
    rank-based estimate is within ``rel_err`` of the true order statistic.
    Non-positive / sub-``min_value`` inputs land in an exact ``zeros``
    bucket (span seconds are non-negative; exact zeros stay exact).

    Mergeable by construction: two sketches with the same ``rel_err`` add
    bucket-wise, and ``merge(a, b)`` is indistinguishable from a sketch
    that ingested both input streams (the windowed-rollup property the
    live plane is built on). Inserts take an optional integer ``weight``
    so the per-class window-latency expansion (``[seconds] * windows`` in
    the offline reporter) costs one bucket update, not ``windows``.
    """

    __slots__ = ("rel_err", "_gamma", "_lg", "_min_value", "_buckets",
                 "zeros", "count", "sum", "min", "max")

    def __init__(self, rel_err: float = 0.01, min_value: float = 1e-9):
        if not 0.0 < rel_err < 1.0:
            raise ValueError(f"rel_err must be in (0, 1), got {rel_err}")
        self.rel_err = float(rel_err)
        self._gamma = (1.0 + rel_err) / (1.0 - rel_err)
        self._lg = math.log(self._gamma)
        self._min_value = float(min_value)
        self._buckets: Dict[int, int] = {}
        self.zeros = 0
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def insert(self, value: float, weight: int = 1) -> None:
        v = float(value)
        w = int(weight)
        if w <= 0:
            return
        self.count += w
        self.sum += v * w
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if v <= self._min_value:
            self.zeros += w
            return
        key = math.ceil(math.log(v) / self._lg)
        self._buckets[key] = self._buckets.get(key, 0) + w

    def merge(self, other: "QuantileSketch") -> None:
        if abs(other.rel_err - self.rel_err) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with rel_err {self.rel_err} != "
                f"{other.rel_err}"
            )
        for key, n in other._buckets.items():
            self._buckets[key] = self._buckets.get(key, 0) + n
        self.zeros += other.zeros
        self.count += other.count
        self.sum += other.sum
        for attr, pick in (("min", min), ("max", max)):
            mine, theirs = getattr(self, attr), getattr(other, attr)
            if theirs is not None:
                setattr(self, attr,
                        theirs if mine is None else pick(mine, theirs))

    # -- estimation ---------------------------------------------------------

    def _bucket_value(self, key: int) -> float:
        v = 2.0 * math.exp(key * self._lg) / (self._gamma + 1.0)
        # exact extremes tighten the estimate for the edge buckets without
        # ever violating the relative-error bound
        if self.max is not None:
            v = min(v, self.max)
        if self.min is not None:
            v = max(v, self.min)
        return v

    def _value_at(self, index: int) -> float:
        """The estimated value of the ``index``-th (0-based) element of
        the sorted inserted multiset."""
        if index < self.zeros:
            return 0.0
        remaining = index - self.zeros
        for key in sorted(self._buckets):
            remaining -= self._buckets[key]
            if remaining < 0:
                return self._bucket_value(key)
        return self.max if self.max is not None else 0.0

    def quantile(self, q: float) -> Optional[float]:
        """The ``q``-th percentile (0..100), linearly interpolated between
        order statistics — the same convention as
        :func:`esr_tpu.obs.report.percentile`, so live and offline agree
        within ``rel_err`` on identical data."""
        if self.count == 0:
            return None
        rank = (q / 100.0) * (self.count - 1)
        lo = int(math.floor(rank))
        hi = int(math.ceil(rank))
        v_lo = self._value_at(lo)
        if lo == hi:
            return v_lo
        v_hi = self._value_at(hi)
        frac = rank - lo
        return v_lo * (1.0 - frac) + v_hi * frac

    # -- wire ---------------------------------------------------------------

    def to_wire(self) -> Dict:
        """JSON-safe serialization. Bucket keys become strings (JSON
        objects cannot key on ints); counts and the running sum are
        carried exactly (ints exactly, floats via repr), so
        ``from_wire(to_wire(sk))`` merges bucket-for-bucket identically
        to ``sk`` — the round-trip half of the rel_err guarantee."""
        return {
            "rel_err": self.rel_err,
            "min_value": self._min_value,
            "buckets": {str(k): n for k, n in self._buckets.items()},
            "zeros": self.zeros,
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
        }

    @classmethod
    def from_wire(cls, doc: Dict) -> "QuantileSketch":
        sk = cls(rel_err=float(doc["rel_err"]),
                 min_value=float(doc["min_value"]))
        sk._buckets = {int(k): int(n) for k, n in doc["buckets"].items()}
        sk.zeros = int(doc["zeros"])
        sk.count = int(doc["count"])
        sk.sum = float(doc["sum"])
        sk.min = None if doc["min"] is None else float(doc["min"])
        sk.max = None if doc["max"] is None else float(doc["max"])
        return sk


class _State:
    """One accumulation scope: the cumulative rollup, or one epoch of the
    window ring. All updates are O(1) dict/scalar ops under the
    aggregator's single lock."""

    __slots__ = (
        "records", "counters", "gauges", "events", "spans", "class_lat",
        "class_windows", "chunk_busy", "chunk_begin", "chunk_end",
        "chunk_kinds", "attr_records", "attr_wall", "attr_wall_x_goodput",
        "requests", "completed_requests", "failed_requests", "statuses",
        "windows_total", "chunk_windows_valid", "windows_skipped",
        "trace_requests", "trace_complete",
        "faults_injected", "recovery_events", "numerics",
    )

    def __init__(self, rel_err: float):
        self.records = 0
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, object] = {}
        self.events: Dict[str, int] = {}
        self.spans: Dict[str, QuantileSketch] = {}
        self.class_lat: Dict[str, QuantileSketch] = {}
        self.class_windows: Dict[str, int] = {}
        self.chunk_busy = 0.0
        self.chunk_begin: Optional[float] = None
        self.chunk_end: Optional[float] = None
        self.chunk_kinds: set = set()
        self.attr_records = 0
        self.attr_wall = 0.0
        self.attr_wall_x_goodput = 0.0
        self.requests = 0
        self.completed_requests = 0
        self.failed_requests = 0
        self.statuses: Dict[str, int] = {}
        self.windows_total = 0
        self.chunk_windows_valid = 0
        self.windows_skipped = 0
        self.trace_requests = 0
        self.trace_complete = 0
        self.faults_injected = 0
        self.recovery_events = 0
        # the numerics plane's per-tag worst-case table (obs/numerics.py
        # ingest/merge_states/rollup — shared with the offline reporter)
        self.numerics: Dict[str, Dict] = {}

    def sketch_for(self, table: Dict[str, QuantileSketch], name: str,
                   rel_err: float) -> QuantileSketch:
        sk = table.get(name)
        if sk is None:
            sk = table[name] = QuantileSketch(rel_err)
        return sk


class LiveAggregator:
    """Streaming rollups + mergeable sketches over the sink record tap
    (module docstring). Attach with :meth:`attach`; every record the sink
    writes is observed exactly once, on the emitting thread, under one
    short lock."""

    def __init__(self, rel_err: float = 0.01, epoch_s: float = 5.0,
                 max_epochs: int = 256, max_roots: int = 8192):
        if epoch_s <= 0:
            raise ValueError(f"epoch_s must be > 0, got {epoch_s}")
        if max_epochs < 2:
            raise ValueError(f"max_epochs must be >= 2, got {max_epochs}")
        self.rel_err = float(rel_err)
        self.epoch_s = float(epoch_s)
        self.max_epochs = int(max_epochs)
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        # Each record updates EXACTLY ONE state — the current epoch (the
        # hot path stays a single pass of O(1) ops). Epochs evicted from
        # the ring merge into the archive; a cumulative snapshot is
        # archive + ring, merged at poll time (rare) instead of per
        # record (hot). Mergeable sketches are what make this exact.
        self._archive = _State(self.rel_err)
        self._epochs: deque = deque()  # (epoch_index, _State), bounded below
        # recent trace roots, FIFO-bounded (insertion-ordered dict): the
        # serving tier emits a request's root span immediately before its
        # terminal event, so a window of the newest max_roots root ids is
        # all the live completeness check ever needs — an unbounded set
        # would leak one entry per request forever, the exact memory
        # hazard ESR013 exists to keep out of this aggregator
        self._roots: Dict[str, None] = {}
        self.max_roots = int(max_roots)
        self.observer_errors = 0

    # -- registration --------------------------------------------------------

    def attach(self, sink) -> "LiveAggregator":
        sink.add_observer(self.observe)
        return self

    def detach(self, sink) -> None:
        sink.remove_observer(self.observe)

    # -- ingestion -----------------------------------------------------------

    def _epoch_state(self, now: float) -> _State:
        idx = int((now - self._t0) / self.epoch_s)
        if not self._epochs or self._epochs[-1][0] != idx:
            self._epochs.append((idx, _State(self.rel_err)))
            while len(self._epochs) > self.max_epochs:
                _, old = self._epochs.popleft()
                _merge_state(self._archive, old)
        return self._epochs[-1][1]

    def observe(self, rec: Dict) -> None:
        """The sink tap: one normalized record dict, exactly as written
        to the JSONL (obs/sink.py ``_write``). Never raises into the hot
        loop — the sink wraps observer dispatch."""
        kind = rec.get("type")
        if kind == "manifest":
            return
        name = rec.get("name", "")
        now = time.monotonic()
        with self._lock:
            st = self._epoch_state(now)
            st.records += 1
            if kind == "counter":
                inc = rec.get("inc", 1)
                try:
                    inc = float(inc)
                except (TypeError, ValueError):
                    inc = 1.0
                st.counters[name] = st.counters.get(name, 0.0) + inc
            elif kind == "gauge":
                st.gauges[name] = rec.get("value")
            elif kind == "span":
                if rec.get("parent_id") is None and rec.get("span_id"):
                    self._roots[rec["span_id"]] = None
                    while len(self._roots) > self.max_roots:
                        self._roots.pop(next(iter(self._roots)))
                self._observe_span(st, name, rec)
            elif kind == "event":
                self._observe_event(st, name, rec)
            elif kind == "numerics":
                _numerics.ingest(st.numerics, rec)
            elif kind == "attribution":
                wall = float(rec.get("wall_s", 0.0) or 0.0)
                good = float(rec.get("goodput", 0.0) or 0.0)
                st.attr_records += 1
                st.attr_wall += wall
                st.attr_wall_x_goodput += wall * good

    def _observe_span(self, st: _State, name: str, rec: Dict) -> None:
        seconds = float(rec.get("seconds", 0.0) or 0.0)
        st.sketch_for(st.spans, name, self.rel_err).insert(seconds)
        if name == "serve_chunk_part":
            cls = rec.get("cls", "default")
            n = int(rec.get("windows", 0) or 0)
            if n > 0:
                st.sketch_for(st.class_lat, cls, self.rel_err).insert(
                    seconds, weight=n
                )
                st.class_windows[cls] = st.class_windows.get(cls, 0) + n
        elif name in ("serve_chunk", "infer_chunk"):
            st.chunk_busy += seconds
            begin, end = rec.get("begin"), rec.get("end")
            if begin is None or end is None:
                end = float(rec.get("t", 0.0))
                begin = end - seconds
            begin, end = float(begin), float(end)
            st.chunk_begin = (begin if st.chunk_begin is None
                              else min(st.chunk_begin, begin))
            st.chunk_end = (end if st.chunk_end is None
                            else max(st.chunk_end, end))
            st.chunk_kinds.add(name)
            # activity gating (ISSUE 12): mirror the offline reporter's
            # computed-vs-skipped tally (serve_chunk ONLY — infer_chunk
            # windows are not serving compute) so
            # serving.active_window_frac evaluates identically live
            # and offline
            if name == "serve_chunk":
                st.chunk_windows_valid += int(rec.get("windows", 0) or 0)
                st.windows_skipped += int(
                    rec.get("skipped_windows", 0) or 0
                )

    def _observe_event(self, st: _State, name: str, rec: Dict) -> None:
        st.events[name] = st.events.get(name, 0) + 1
        if name == "serve_gating_flush":
            # trailing gated windows with no chunk span to ride
            # (serving/server.py drain path) — keep live == offline
            st.windows_skipped += int(rec.get("skipped", 0) or 0)
        if name == "fault_injected":
            st.faults_injected += 1
        elif name.startswith("recovery_"):
            st.recovery_events += 1
        elif name == "serve_request_done":
            status = rec.get("status") or (
                "ok" if rec.get("completed", False) else "bad_stream"
            )
            st.statuses[status] = st.statuses.get(status, 0) + 1
            # the reporter's status taxonomy, shared (docs/RESILIENCE.md):
            # rootless terminals (shed, replica_lost, retry-exhausted —
            # the emitting replica never ran the request root) are skipped
            # by trace completeness, and continued terminals (shed,
            # migrated, replica_lost — the request lives on elsewhere)
            # never count toward request/window totals. This is what lets
            # router-level ledger records join a merge without a migrated
            # stream reading as a failed request.
            if status not in _ROOTLESS_STATUSES:
                # live completeness: the root span (serve_request) is
                # emitted immediately before the terminal event, so
                # parent-of-done resolving to a seen root is the live
                # analogue of the reporter's parent-chain walk
                st.trace_requests += 1
                if rec.get("parent_id") in self._roots:
                    st.trace_complete += 1
            if status in _CONTINUED_STATUSES:
                return
            st.requests += 1
            st.windows_total += int(rec.get("windows", 0) or 0)
            if rec.get("completed", False):
                st.completed_requests += 1
            else:
                st.failed_requests += 1

    # -- snapshots -----------------------------------------------------------

    def _merged_state(self, window_s: Optional[float], now: float) -> _State:
        """Archive + ring for the cumulative view; ring-only for a
        window. A window may reach at most ``epoch_s * max_epochs``
        seconds back (default ~21 min — far beyond the burn-rate pair);
        older epochs live only in the archive."""
        merged = _State(self.rel_err)
        if window_s is None:
            _merge_state(merged, self._archive)
            for _idx, st in self._epochs:
                _merge_state(merged, st)
            return merged
        cutoff_idx = int((now - self._t0 - window_s) / self.epoch_s)
        for idx, st in self._epochs:
            # include every epoch overlapping the window (coarse on
            # purpose: epoch_s granularity, documented)
            if idx >= cutoff_idx:
                _merge_state(merged, st)
        return merged

    def snapshot(self, window_s: Optional[float] = None) -> Dict:
        """The report-shaped live rollup (the offline reporter's dotted
        namespace — ``goodput.value``, ``spans.<name>.p99_ms``,
        ``serving.classes.<cls>.window_latency_p99_ms``,
        ``counters.<name>``, ``traces.incomplete`` — so configs/slo.yml
        evaluates unchanged). ``window_s`` restricts to the trailing
        window; either way the result is an epoch MERGE built at poll
        time, so the record hot path only ever touches one epoch state."""
        now = time.monotonic()
        with self._lock:
            st = self._merged_state(
                None if window_s is None else float(window_s), now
            )
            return self._render(st, window_s, now)

    def merged_state(self, window_s: Optional[float] = None) -> "_State":
        """The merged accumulation state itself (cumulative, or the
        trailing window) — a fresh :class:`_State` the caller owns. This
        is the in-process twin of parsing a ``/snapshot`` wire document:
        fleet-level consumers merge these instead of re-rendering."""
        now = time.monotonic()
        with self._lock:
            return self._merged_state(
                None if window_s is None else float(window_s), now
            )

    def snapshot_wire(self, windows: Iterable[float] = ()) -> Dict:
        """The versioned wire document (module docstring): the cumulative
        accumulation state plus one state per requested trailing window,
        serialized with :func:`state_to_wire`. One call, one lock pass —
        this is the single fetch the fleet plane lives on."""
        now = time.monotonic()
        with self._lock:
            return {
                "version": SNAPSHOT_WIRE_VERSION,
                "rel_err": self.rel_err,
                "uptime_s": round(now - self._t0, 3),
                "state": state_to_wire(self._merged_state(None, now)),
                "window_states": {
                    str(float(w)): state_to_wire(
                        self._merged_state(float(w), now)
                    )
                    for w in windows
                },
            }

    def _render(self, st: _State, window_s, now: float) -> Dict:
        return render_state(st, window_s=window_s,
                            uptime_s=round(now - self._t0, 3),
                            rel_err=self.rel_err)


def render_state(st: "_State", window_s: Optional[float] = None,
                 uptime_s: Optional[float] = None,
                 rel_err: float = 0.01) -> Dict:
    """Render one accumulation state into the report-shaped dotted
    namespace (:meth:`LiveAggregator.snapshot`'s body, shared so the
    fleet plane renders MERGED states through the exact same code path —
    ``configs/slo*.yml`` cannot tell a fleet snapshot from a replica
    one)."""
    goodput: Dict = {"value": None, "source": None}
    if st.attr_records and st.attr_wall > 0:
        goodput = {
            "value": round(st.attr_wall_x_goodput / st.attr_wall, 6),
            "source": "attribution",
            "records": st.attr_records,
        }
    elif st.chunk_begin is not None:
        wall = max((st.chunk_end or 0.0) - st.chunk_begin, 1e-9)
        goodput = {
            "value": round(min(st.chunk_busy / wall, 1.0), 6),
            "source": ("serving" if "serve_chunk" in st.chunk_kinds
                       else "inference"),
            "busy_s": round(st.chunk_busy, 6),
            "wall_s": round(wall, 6),
        }
    spans_out = {
        name: {
            "count": sk.count,
            "total_s": round(sk.sum, 6),
            "p50_ms": _round(sk.quantile(50), 1e3),
            "p99_ms": _round(sk.quantile(99), 1e3),
            "max_ms": _round(sk.max, 1e3),
        }
        for name, sk in sorted(st.spans.items())
    }
    serving = {
        "requests": st.requests,
        "completed": st.completed_requests,
        "errors": st.failed_requests,
        "statuses": {k: st.statuses[k] for k in sorted(st.statuses)},
        "windows": st.windows_total,
        "windows_skipped": st.windows_skipped,
        "active_window_frac": (
            round(st.chunk_windows_valid
                  / (st.chunk_windows_valid + st.windows_skipped), 6)
            if (st.chunk_windows_valid + st.windows_skipped) else None
        ),
        "preemptions": st.events.get("serve_preempt", 0),
        "backpressure": st.counters.get("serve_backpressure", 0.0),
        "classes": {
            cls: {
                "windows": st.class_windows.get(cls, 0),
                "window_latency_p50_ms": _round(sk.quantile(50), 1e3),
                "window_latency_p99_ms": _round(sk.quantile(99), 1e3),
            }
            for cls, sk in sorted(st.class_lat.items())
        },
    }
    return {
        "live": True,
        "window_s": window_s,
        "uptime_s": uptime_s,
        "records": st.records,
        "sketch_rel_err": rel_err,
        "goodput": goodput,
        "spans": spans_out,
        "counters": {k: st.counters[k] for k in sorted(st.counters)},
        "gauges": {k: st.gauges[k] for k in sorted(st.gauges)},
        "events": {k: st.events[k] for k in sorted(st.events)},
        "serving": serving,
        "traces": {
            "requests": st.trace_requests,
            "complete": st.trace_complete,
            "incomplete": st.trace_requests - st.trace_complete,
        },
        "faults": {
            "injected": st.faults_injected,
            "recovery_events": st.recovery_events,
        },
        "numerics": _numerics.rollup(st.numerics),
    }


def _merge_state(dst: _State, src: _State) -> None:
    dst.records += src.records
    for k, v in src.counters.items():
        dst.counters[k] = dst.counters.get(k, 0.0) + v
    dst.gauges.update(src.gauges)  # ring order == time order: last wins
    for k, v in src.events.items():
        dst.events[k] = dst.events.get(k, 0) + v
    for table_name in ("spans", "class_lat"):
        dst_t = getattr(dst, table_name)
        for k, sk in getattr(src, table_name).items():
            mine = dst_t.get(k)
            if mine is None:
                mine = dst_t[k] = QuantileSketch(sk.rel_err)
            mine.merge(sk)
    for k, v in src.class_windows.items():
        dst.class_windows[k] = dst.class_windows.get(k, 0) + v
    dst.chunk_busy += src.chunk_busy
    if src.chunk_begin is not None:
        dst.chunk_begin = (src.chunk_begin if dst.chunk_begin is None
                           else min(dst.chunk_begin, src.chunk_begin))
    if src.chunk_end is not None:
        dst.chunk_end = (src.chunk_end if dst.chunk_end is None
                         else max(dst.chunk_end, src.chunk_end))
    dst.chunk_kinds |= src.chunk_kinds
    dst.attr_records += src.attr_records
    dst.attr_wall += src.attr_wall
    dst.attr_wall_x_goodput += src.attr_wall_x_goodput
    dst.requests += src.requests
    dst.completed_requests += src.completed_requests
    dst.failed_requests += src.failed_requests
    for k, v in src.statuses.items():
        dst.statuses[k] = dst.statuses.get(k, 0) + v
    dst.windows_total += src.windows_total
    dst.chunk_windows_valid += src.chunk_windows_valid
    dst.windows_skipped += src.windows_skipped
    dst.trace_requests += src.trace_requests
    dst.trace_complete += src.trace_complete
    dst.faults_injected += src.faults_injected
    dst.recovery_events += src.recovery_events
    _numerics.merge_states(dst.numerics, src.numerics)


# ---------------------------------------------------------------------------
# the snapshot wire format (obs v5): every _State slot, JSON-safe


def state_to_wire(st: _State) -> Dict:
    """Serialize one accumulation state — every ``_State`` slot, sketches
    via :meth:`QuantileSketch.to_wire`, ``chunk_kinds`` as a sorted list,
    the numerics table verbatim (it is already JSON-scalar rows)."""
    return {
        "records": st.records,
        "counters": dict(st.counters),
        "gauges": dict(st.gauges),
        "events": dict(st.events),
        "spans": {k: sk.to_wire() for k, sk in st.spans.items()},
        "class_lat": {k: sk.to_wire() for k, sk in st.class_lat.items()},
        "class_windows": dict(st.class_windows),
        "chunk_busy": st.chunk_busy,
        "chunk_begin": st.chunk_begin,
        "chunk_end": st.chunk_end,
        "chunk_kinds": sorted(st.chunk_kinds),
        "attr_records": st.attr_records,
        "attr_wall": st.attr_wall,
        "attr_wall_x_goodput": st.attr_wall_x_goodput,
        "requests": st.requests,
        "completed_requests": st.completed_requests,
        "failed_requests": st.failed_requests,
        "statuses": dict(st.statuses),
        "windows_total": st.windows_total,
        "chunk_windows_valid": st.chunk_windows_valid,
        "windows_skipped": st.windows_skipped,
        "trace_requests": st.trace_requests,
        "trace_complete": st.trace_complete,
        "faults_injected": st.faults_injected,
        "recovery_events": st.recovery_events,
        "numerics": {tag: dict(row) for tag, row in st.numerics.items()},
    }


def state_from_wire(doc: Dict) -> _State:
    """Rebuild a :class:`_State` from :func:`state_to_wire` output. The
    round-trip is exact (ints exactly; floats survive JSON via repr), so
    merging a parsed state is indistinguishable from merging the
    original — pinned in ``tests/test_fleet_obs.py``."""
    st = _State(0.01)  # per-sketch rel_err rides each sketch's own wire
    st.records = int(doc["records"])
    st.counters = {str(k): float(v) for k, v in doc["counters"].items()}
    st.gauges = dict(doc["gauges"])
    st.events = {str(k): int(v) for k, v in doc["events"].items()}
    st.spans = {
        str(k): QuantileSketch.from_wire(v) for k, v in doc["spans"].items()
    }
    st.class_lat = {
        str(k): QuantileSketch.from_wire(v)
        for k, v in doc["class_lat"].items()
    }
    st.class_windows = {
        str(k): int(v) for k, v in doc["class_windows"].items()
    }
    st.chunk_busy = float(doc["chunk_busy"])
    st.chunk_begin = (None if doc["chunk_begin"] is None
                      else float(doc["chunk_begin"]))
    st.chunk_end = (None if doc["chunk_end"] is None
                    else float(doc["chunk_end"]))
    st.chunk_kinds = set(doc["chunk_kinds"])
    st.attr_records = int(doc["attr_records"])
    st.attr_wall = float(doc["attr_wall"])
    st.attr_wall_x_goodput = float(doc["attr_wall_x_goodput"])
    st.requests = int(doc["requests"])
    st.completed_requests = int(doc["completed_requests"])
    st.failed_requests = int(doc["failed_requests"])
    st.statuses = {str(k): int(v) for k, v in doc["statuses"].items()}
    st.windows_total = int(doc["windows_total"])
    st.chunk_windows_valid = int(doc["chunk_windows_valid"])
    st.windows_skipped = int(doc["windows_skipped"])
    st.trace_requests = int(doc["trace_requests"])
    st.trace_complete = int(doc["trace_complete"])
    st.faults_injected = int(doc["faults_injected"])
    st.recovery_events = int(doc["recovery_events"])
    st.numerics = {
        str(tag): dict(row) for tag, row in doc["numerics"].items()
    }
    return st


def parse_snapshot_wire(doc: Dict) -> Dict:
    """Parse one ``/snapshot`` wire document back into accumulation
    state: ``{"version", "rel_err", "uptime_s", "state": _State,
    "windows": {window_s: _State}}`` plus the live-plane context keys
    (``replica``, ``health``, ``slo_verdict``) passed through untouched.

    Raises :class:`ValueError` LOUDLY on a version mismatch or a torn
    document — an unparseable snapshot must never be merged into a fleet
    rollup (the caller marks the replica unhealthy instead)."""
    if not isinstance(doc, dict):
        raise ValueError(
            f"snapshot wire document must be a dict, got "
            f"{type(doc).__name__}"
        )
    version = doc.get("version")
    if version != SNAPSHOT_WIRE_VERSION:
        raise ValueError(
            f"snapshot wire version {version!r} is not the supported "
            f"{SNAPSHOT_WIRE_VERSION} — refusing to merge"
        )
    try:
        parsed: Dict = {
            "version": int(version),
            "rel_err": float(doc["rel_err"]),
            "uptime_s": float(doc.get("uptime_s", 0.0)),
            "state": state_from_wire(doc["state"]),
            "windows": {
                float(k): state_from_wire(v)
                for k, v in (doc.get("window_states") or {}).items()
            },
        }
    except (KeyError, TypeError, ValueError, AttributeError) as exc:
        raise ValueError(f"torn snapshot wire document: {exc!r}") from exc
    for key in ("replica", "health", "slo_verdict"):
        if key in doc:
            parsed[key] = doc[key]
    return parsed
