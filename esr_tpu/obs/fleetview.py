"""esr_tpu.obs — the fleet view (obs v5, docs/OBSERVABILITY.md).

Per-replica live planes (obs/http.py) answer for ONE process; the
ROADMAP's autoscaler needs the MERGED picture — fleet-wide p99, fleet
queue depth, a burn rate over the whole error budget. This module is
that layer, built on the property obs v3 pinned from the start:
``QuantileSketch`` merge == concat, so N replicas' accumulation states
(fetched as ``/snapshot`` wire documents — ``aggregate.snapshot_wire``)
merge into one state that is indistinguishable from a single aggregator
having observed every record. VirtualFlow's decoupling (PAPERS.md,
arXiv 2009.09523) applied to telemetry: consumers read classes and
SLOs, never individual replicas.

- :class:`FleetAggregator` — the scraper/merger. Watches N replica
  snapshot URLs (or is fed parsed documents by the
  ``ReplicaSupervisor`` — one fetch per replica per poll serves BOTH
  death detection and the fleet view), tracks per-replica staleness,
  and renders merged snapshots in the SAME dotted namespace the
  offline reporter and per-replica aggregator share, so
  ``configs/slo*.yml`` evaluates fleet snapshots unchanged.
- **Staleness, never silence**: a replica that has missed
  ``scrape_budget`` consecutive scrapes (or never produced a parseable
  snapshot) is marked STALE and excluded from every merge, with the
  exclusion annotated on the snapshot's ``fleet`` section — a fleet
  rollup silently missing a replica would turn a dead replica into a
  rosier p99.
- :class:`ScalingPolicy` + the advisory signal: ``desired_replicas``
  computed from merged queue depth and per-class p99 burn with
  hysteresis (``hold_polls`` consecutive agreeing polls before the
  advice moves) — the exact input a real-process autoscaler actuates,
  emitted as a gauge and on ``/fleet``.
- :class:`FleetTelemetryServer` / :func:`start_fleet_plane` — the fleet
  HTTP surface: ``/metrics`` (merged rollup + a bounded ``replica``
  label block), ``/slo`` (multi-window burn over MERGED windows, shared
  semantics with the per-replica endpoint via
  ``report.evaluate_slo_window``), ``/healthz`` (quorum: fraction of
  watched replicas fresh AND healthy), ``/fleet`` (topology: per-replica
  health, staleness, queue depth, lane occupancy, ring ownership, the
  scaling signal), ``/snapshot`` (the fleet's own merged state in the
  replica wire format — fleet views compose).

Stdlib-only and host-side only, like all of ``esr_tpu.obs``. Thread
discipline (CX gate): one lock guards the ledger/locals; HTTP fetches
run OUTSIDE the lock; the optional scraper is a daemon thread stopped
via Event + timed join (the ``ReplicaSupervisor.start`` pattern).
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from esr_tpu.obs.aggregate import (
    SNAPSHOT_WIRE_VERSION,
    _State,
    _merge_state,
    parse_snapshot_wire,
    render_state,
    state_to_wire,
)
from esr_tpu.obs.http import parse_windows_query, render_prometheus

logger = logging.getLogger(__name__)

__all__ = [
    "http_fetch",
    "SnapshotClient",
    "ScalingPolicy",
    "FleetAggregator",
    "FleetTelemetryServer",
    "FleetPlane",
    "start_fleet_plane",
]


def http_fetch(url: str, timeout_s: float) -> Tuple[int, str]:
    """GET ``url``; returns ``(status, body)`` — an HTTPError IS an
    answer (its status and body come back, 429/503 are valid verdicts).
    Raises on transport failure (connect refused, timeout): the
    heartbeat-miss / staleness signal."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return int(resp.status), resp.read().decode("utf-8", "replace")
    except urllib.error.HTTPError as e:
        return int(e.code), e.read().decode("utf-8", "replace")


class SnapshotClient:
    """One replica ``/snapshot`` fetch+parse. The error taxonomy is the
    contract: transport failures (``OSError`` family) propagate — the
    replica may be DEAD; a replica that ANSWERS but with a non-200 or an
    unparseable/mis-versioned document raises ``ValueError`` — the
    replica is alive but must never be merged (parse_snapshot_wire's
    loud-rejection rule)."""

    def __init__(self, timeout_s: float = 1.0, fetch=None):
        self.timeout_s = float(timeout_s)
        self._fetch = fetch if fetch is not None else http_fetch

    def fetch(self, url: str) -> Tuple[Dict, int]:
        """Returns ``(parsed_snapshot, wire_bytes)``."""
        status, body = self._fetch(url, self.timeout_s)
        if status != 200:
            raise ValueError(
                f"snapshot endpoint answered {status}, not 200"
            )
        return parse_snapshot_wire(json.loads(body)), len(body)


# ---------------------------------------------------------------------------
# the advisory scaling signal


class ScalingPolicy:
    """Inputs of the ``desired_replicas`` formula (docs/OBSERVABILITY.md
    "The fleet view"):

    ``raw = clamp(max(min_replicas, ceil(queue_total /
    target_queue_per_replica), healthy + 1 if burning), min..max)``

    where *burning* means any fresh replica's own ``/slo`` verdict is
    "page" or any merged fast-window class p99 exceeds its
    ``class_p99_target_ms`` entry. The advice only MOVES after
    ``hold_polls`` consecutive polls agree on the same new value
    (hysteresis — a one-poll queue spike must not flap the fleet)."""

    __slots__ = ("target_queue_per_replica", "min_replicas",
                 "max_replicas", "hold_polls", "class_p99_target_ms")

    def __init__(
        self,
        target_queue_per_replica: float = 8.0,
        min_replicas: int = 1,
        max_replicas: int = 8,
        hold_polls: int = 2,
        class_p99_target_ms: Optional[Dict[str, float]] = None,
    ):
        if target_queue_per_replica <= 0:
            raise ValueError(
                f"target_queue_per_replica must be > 0, got "
                f"{target_queue_per_replica}"
            )
        if not 1 <= int(min_replicas) <= int(max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}"
            )
        if hold_polls < 1:
            raise ValueError(f"hold_polls must be >= 1, got {hold_polls}")
        self.target_queue_per_replica = float(target_queue_per_replica)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.hold_polls = int(hold_polls)
        self.class_p99_target_ms = {
            str(k): float(v)
            for k, v in (class_p99_target_ms or {}).items()
        }

    @classmethod
    def from_yaml(cls, path: str) -> "ScalingPolicy":
        """Load ``configs/fleet_scale.yml`` (schema 1). Fail fast on an
        unknown schema — a misread policy silently scaling a fleet is
        the exact failure mode the wire version check exists for."""
        import yaml  # lazy: obs stays importable without PyYAML

        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        if doc.get("schema") != 1:
            raise ValueError(
                f"unsupported fleet_scale schema {doc.get('schema')!r} "
                f"in {path} (supported: 1)"
            )
        return cls(
            target_queue_per_replica=doc.get(
                "target_queue_per_replica", 8.0),
            min_replicas=doc.get("min_replicas", 1),
            max_replicas=doc.get("max_replicas", 8),
            hold_polls=doc.get("hold_polls", 2),
            class_p99_target_ms=doc.get("class_p99_target_ms"),
        )


# ---------------------------------------------------------------------------
# the merger


def _fresh_row(row: Dict, scrape_budget: int) -> Tuple[bool, Optional[str]]:
    """(fresh?, exclusion reason). Fresh = has a parseable document and
    is within its scrape budget; the budget tolerates transient misses
    by merging the LAST GOOD document until the budget runs out."""
    if row["doc"] is None:
        return False, ("never_scraped" if row["scrapes"] == 0
                       else "no_parseable_snapshot")
    if row["misses"] >= scrape_budget:
        return False, "scrape_budget_exhausted"
    return True, None


class FleetAggregator:
    """Merged live rollups over N replica ``/snapshot`` documents plus
    any locally-attached aggregators (the router's own ledger records —
    handoffs, sheds, fail-over terminals — join the merge through
    :meth:`attach_local`, so fleet totals classify every journey
    segment, docs/RESILIENCE.md).

    Feed it either way (the ledger semantics are identical):

    - :meth:`scrape_once` — pull mode: fetch every watched URL itself
      (fetches outside the lock);
    - :meth:`ingest` — push mode: the ``ReplicaSupervisor`` hands over
      each poll's parsed document (or ``None`` for a miss), so one HTTP
      fetch per replica per poll serves BOTH death detection and the
      fleet view.

    Staleness (module docstring): ``misses >= scrape_budget`` or no
    parseable document ever → excluded from every merge, annotated on
    ``snapshot()['fleet']['excluded']``, never silently merged.
    """

    def __init__(
        self,
        rel_err: float = 0.01,
        windows: Tuple[float, float] = (60.0, 300.0),
        scrape_budget: int = 3,
        timeout_s: float = 1.0,
        fetch=None,
        policy: Optional[ScalingPolicy] = None,
    ):
        if scrape_budget < 1:
            raise ValueError(
                f"scrape_budget must be >= 1, got {scrape_budget}")
        if not (len(windows) == 2 and 0 < windows[0] <= windows[1]):
            raise ValueError(
                f"windows must be (fast_s, slow_s) with 0 < fast <= slow, "
                f"got {windows!r}"
            )
        self.rel_err = float(rel_err)
        self.windows = (float(windows[0]), float(windows[1]))
        self.scrape_budget = int(scrape_budget)
        self.policy = policy if policy is not None else ScalingPolicy()
        self._client = SnapshotClient(timeout_s=timeout_s, fetch=fetch)
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._targets: Dict[str, Optional[str]] = {}
        self._ledger: Dict[str, Dict] = {}
        self._locals: Dict[str, object] = {}
        # scaling-signal hysteresis state (one tick per covered round)
        self._round_seen: set = set()
        self._signal: Dict = {
            "desired_replicas": None, "raw": None, "healthy": 0,
            "queue_depth": 0.0, "page": False, "classes_over": [],
            "pending": None, "pending_polls": 0, "ticks": 0,
        }

    # -- watch list ----------------------------------------------------------

    def _new_row(self, url: Optional[str]) -> Dict:
        return {
            "url": url, "scrapes": 0, "misses": 0, "doc": None,
            "wire_bytes": None, "uptime_s": None, "healthy": None,
            "slo_verdict": None, "last_error": None,
        }

    def watch(self, replica_id: str, snapshot_url: Optional[str]) -> None:
        """Watch (or re-point) one replica's ``/snapshot`` URL. ``None``
        keeps the replica ON the ledger with no endpoint — every scrape
        misses, so it goes stale on budget (the fenced/killed-replica
        path)."""
        with self._lock:
            self._targets[replica_id] = snapshot_url
            row = self._ledger.setdefault(
                replica_id, self._new_row(snapshot_url))
            row["url"] = snapshot_url

    def unwatch(self, replica_id: str) -> None:
        with self._lock:
            self._targets.pop(replica_id, None)
            self._ledger.pop(replica_id, None)
            self._round_seen.discard(replica_id)

    def attach_local(self, name: str, aggregator) -> None:
        """A same-process :class:`LiveAggregator` that joins every merge
        directly (no wire, never stale) — the router's ledger stream."""
        with self._lock:
            self._locals[name] = aggregator

    # -- feeding -------------------------------------------------------------

    def ingest(self, replica_id: str, parsed: Optional[Dict],
               wire_bytes: Optional[int] = None,
               error: Optional[str] = None,
               unusable: bool = False) -> None:
        """Record one poll's outcome for ``replica_id``: a parsed
        snapshot document (``parse_snapshot_wire`` output), or ``None``
        for a miss (transport failure — the last GOOD document keeps
        merging until the scrape budget runs out) or, with
        ``unusable=True``, an answered-but-unparseable reply whose
        stored document can no longer be trusted as "last good". A
        mis-matched ``rel_err`` is rejected loudly here (merging it
        would silently void the quantile guarantee)."""
        if parsed is not None and abs(
                parsed["rel_err"] - self.rel_err) > 1e-12:
            error = (f"snapshot rel_err {parsed['rel_err']} != fleet "
                     f"{self.rel_err} — refusing to merge")
            logger.warning("fleetview: %s: %s", replica_id, error)
            parsed = None
            unusable = True
        with self._lock:
            row = self._ledger.setdefault(
                replica_id, self._new_row(self._targets.get(replica_id)))
            row["scrapes"] += 1
            if parsed is None:
                row["misses"] += 1
                row["last_error"] = error
                if unusable:
                    row["doc"] = None
            else:
                row["misses"] = 0
                row["doc"] = parsed
                row["wire_bytes"] = wire_bytes
                row["uptime_s"] = parsed.get("uptime_s")
                health = parsed.get("health") or {}
                row["healthy"] = bool(health.get("healthy", False))
                row["slo_verdict"] = parsed.get("slo_verdict")
                row["last_error"] = None
            self._round_seen.add(replica_id)
            # a poll round is COMPLETE once it covered every watched
            # replica that could still answer — a budget-exhausted
            # (stale) replica must not stall the signal forever: its
            # push-mode feeder (the supervisor) unwatches dead replicas,
            # so it would never be "seen" again
            blocking = set()
            for rid in self._targets:
                other = self._ledger.get(rid)
                if (other is None or other["scrapes"] == 0
                        or other["misses"] < self.scrape_budget):
                    blocking.add(rid)
            if self._round_seen >= blocking:
                self._round_seen.clear()
                self._tick_signal_locked()

    def scrape_once(self) -> Dict[str, bool]:
        """Pull mode: one scrape pass over every watched replica
        (fetches OUTSIDE the lock). Returns ``{replica_id: fresh_doc?}``.
        The scrape URL pins this fleet's windows via ``?window_s=`` so
        merged-window evaluation never depends on replica defaults."""
        with self._lock:
            targets = dict(self._targets)
        qs = "window_s=" + ",".join(str(w) for w in self.windows)
        results: Dict[str, bool] = {}
        for rid, url in targets.items():
            parsed, nbytes, error, unusable = None, None, None, False
            if url is None:
                error = "no endpoint (replica down)"
            else:
                sep = "&" if "?" in url else "?"
                try:
                    parsed, nbytes = self._client.fetch(f"{url}{sep}{qs}")
                except ValueError as e:
                    # answered, unusable: alive but never merged
                    error, unusable = str(e), True
                except Exception as e:  # esr: noqa(ESR012)
                    # invariant: transport failure IS the staleness
                    # signal — recorded on the ledger by the ingest
                    # below, surfaced on /fleet (never swallowed)
                    error = repr(e)
            self.ingest(rid, parsed, wire_bytes=nbytes, error=error,
                        unusable=unusable)
            results[rid] = parsed is not None
        return results

    # -- the merged view -----------------------------------------------------

    def _window_state(self, parsed: Dict, window_s: Optional[float],
                      rid: str) -> _State:
        if window_s is None:
            return parsed["state"]
        st = parsed["windows"].get(float(window_s))
        if st is None:
            raise ValueError(
                f"replica {rid!r} snapshot carries windows "
                f"{sorted(parsed['windows'])}, not {window_s} — scrape "
                f"with ?window_s= matching the fleet windows"
            )
        return st

    def merged_state(self, window_s: Optional[float] = None
                     ) -> Tuple[_State, List[str], Dict[str, str]]:
        """Merge every FRESH replica document (+ locals) for the
        cumulative view or one trailing window. Returns
        ``(state, merged_ids, excluded)`` where ``excluded`` maps stale
        replica ids to their exclusion reason — callers must surface it
        (the never-silently-merged rule)."""
        # local states first, OUTSIDE our lock (each local aggregator
        # has its own lock; never nest them)
        with self._lock:
            locals_now = dict(self._locals)
        local_states = {
            name: agg.merged_state(window_s)
            for name, agg in locals_now.items()
        }
        merged = _State(self.rel_err)
        merged_ids: List[str] = []
        excluded: Dict[str, str] = {}
        with self._lock:
            for rid in sorted(self._ledger):
                row = self._ledger[rid]
                fresh, reason = _fresh_row(row, self.scrape_budget)
                if not fresh:
                    excluded[rid] = reason
                    continue
                _merge_state(
                    merged, self._window_state(row["doc"], window_s, rid))
                merged_ids.append(rid)
        for name in sorted(local_states):
            _merge_state(merged, local_states[name])
            merged_ids.append(f"local:{name}")
        return merged, merged_ids, excluded

    def snapshot(self, window_s: Optional[float] = None) -> Dict:
        """The MERGED report-shaped rollup (``render_state`` — the same
        renderer as a replica snapshot, so ``configs/slo*.yml`` dots in
        unchanged) plus a ``fleet`` section: who merged, who was
        excluded and why, the per-replica table, the scaling signal."""
        st, merged_ids, excluded = self.merged_state(window_s)
        snap = render_state(
            st, window_s=window_s,
            uptime_s=round(time.monotonic() - self._t0, 3),
            rel_err=self.rel_err,
        )
        snap["fleet"] = {
            "merged": merged_ids,
            "excluded": excluded,
            "replicas": self.replica_table(),
            "scaling": self.scaling_signal(),
        }
        return snap

    def snapshot_wire(self, windows: Iterable[float] = ()) -> Dict:
        """The fleet's own MERGED state as the same versioned wire
        document a replica serves — fleet views compose: a higher-level
        aggregator scrapes this fleet's ``/snapshot`` exactly like a
        replica's (exclusions still surface on ``/fleet``, never inside
        the wire doc)."""
        cum, _ids, _exc = self.merged_state(None)
        return {
            "version": SNAPSHOT_WIRE_VERSION,
            "rel_err": self.rel_err,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "state": state_to_wire(cum),
            "window_states": {
                str(float(w)): state_to_wire(self.merged_state(float(w))[0])
                for w in windows
            },
        }

    def replica_table(self) -> Dict[str, Dict]:
        """Per-replica supervision/merge status: health, staleness (with
        reason), scrape ledger, queue depth + lane occupancy (the
        engine's per-round gauges, read from the replica's own cumulative
        state), wire bytes of the last snapshot."""
        with self._lock:
            out: Dict[str, Dict] = {}
            for rid in sorted(self._ledger):
                row = self._ledger[rid]
                fresh, reason = _fresh_row(row, self.scrape_budget)
                gauges = (row["doc"]["state"].gauges
                          if row["doc"] is not None else {})
                out[rid] = {
                    "url": row["url"],
                    "healthy": row["healthy"],
                    "slo_verdict": row["slo_verdict"],
                    "stale": not fresh,
                    "stale_reason": reason,
                    "scrapes": row["scrapes"],
                    "misses": row["misses"],
                    "last_error": row["last_error"],
                    "uptime_s": row["uptime_s"],
                    "wire_bytes": row["wire_bytes"],
                    "queue_depth": gauges.get("serve_queue_depth"),
                    "lane_occupancy": gauges.get("serve_lane_occupancy"),
                }
            return out

    def quorum_stats(self) -> Dict:
        """Healthy-replica fraction over the WATCHED set (locals are the
        router's own process — not quorum members)."""
        with self._lock:
            watched = len(self._targets)
            fresh_healthy = 0
            fresh = 0
            for rid in self._targets:
                row = self._ledger.get(rid)
                if row is None:
                    continue
                ok, _ = _fresh_row(row, self.scrape_budget)
                if ok:
                    fresh += 1
                    if row["healthy"]:
                        fresh_healthy += 1
        return {
            "watched": watched,
            "fresh": fresh,
            "healthy": fresh_healthy,
            "fraction": (round(fresh_healthy / watched, 6)
                         if watched else None),
        }

    # -- the scaling signal --------------------------------------------------

    def _tick_signal_locked(self) -> None:
        """One hysteresis step (ScalingPolicy docstring), taken each
        time a poll round has covered every watched replica. Lock held
        by the caller; pure dict/sketch math, no IO."""
        policy = self.policy
        healthy = 0
        queue_total = 0.0
        page = False
        fast_states: List[_State] = []
        for rid in self._targets:
            row = self._ledger.get(rid)
            if row is None:
                continue
            fresh, _ = _fresh_row(row, self.scrape_budget)
            if not fresh:
                continue
            if row["healthy"]:
                healthy += 1
            if row["slo_verdict"] == "page":
                page = True
            gauges = row["doc"]["state"].gauges
            try:
                queue_total += float(gauges.get("serve_queue_depth") or 0)
            except (TypeError, ValueError):
                pass
            fast = row["doc"]["windows"].get(self.windows[0])
            if fast is not None:
                fast_states.append(fast)
        classes_over: List[str] = []
        if policy.class_p99_target_ms and fast_states:
            merged = _State(self.rel_err)
            for st in fast_states:
                _merge_state(merged, st)
            for cls, target_ms in sorted(
                    policy.class_p99_target_ms.items()):
                sk = merged.class_lat.get(cls)
                if sk is None or sk.count == 0:
                    continue
                p99 = sk.quantile(99)
                if p99 is not None and p99 * 1e3 > target_ms:
                    classes_over.append(cls)
        burning = page or bool(classes_over)
        raw = max(
            policy.min_replicas,
            int(math.ceil(queue_total / policy.target_queue_per_replica)),
        )
        if burning:
            raw = max(raw, healthy + 1)
        raw = max(policy.min_replicas, min(policy.max_replicas, raw))
        sig = self._signal
        sig.update(raw=raw, healthy=healthy,
                   queue_depth=round(queue_total, 6), page=page,
                   classes_over=classes_over, ticks=sig["ticks"] + 1)
        if sig["desired_replicas"] is None:
            # first covered round: the advice has to start somewhere
            sig.update(desired_replicas=raw, pending=None,
                       pending_polls=0)
        elif raw == sig["desired_replicas"]:
            sig.update(pending=None, pending_polls=0)
        else:
            if raw == sig["pending"]:
                sig["pending_polls"] += 1
            else:
                sig.update(pending=raw, pending_polls=1)
            if sig["pending_polls"] >= policy.hold_polls:
                sig.update(desired_replicas=raw, pending=None,
                           pending_polls=0)

    def scaling_signal(self) -> Dict:
        with self._lock:
            return dict(self._signal)


# ---------------------------------------------------------------------------
# the fleet HTTP surface


def fleet_metrics_block(table: Dict[str, Dict], signal: Dict,
                        quorum: Dict, prefix: str = "esr_fleet") -> str:
    """The per-replica + signal Prometheus block appended to the merged
    exposition. The ``replica`` label vocabulary is the WATCHED fleet
    ledger — bounded by fleet configuration, never per-request
    (ESR013)."""
    def fmt(v) -> str:
        if v is None:
            return "NaN"
        if isinstance(v, bool):
            return "1" if v else "0"
        return repr(float(v))

    lines: List[str] = []
    for name, key in (("up", "healthy"), ("stale", "stale"),
                      ("queue_depth", "queue_depth"),
                      ("lane_occupancy", "lane_occupancy"),
                      ("scrape_misses", "misses"),
                      ("snapshot_bytes", "wire_bytes")):
        metric = f"{prefix}_replica_{name}"
        lines.append(f"# TYPE {metric} gauge")
        for rid in sorted(table):
            lines.append(
                f'{metric}{{replica="{rid}"}} {fmt(table[rid].get(key))}'
            )
    lines.append(f"# TYPE {prefix}_replicas_watched gauge")
    lines.append(f"{prefix}_replicas_watched {fmt(quorum.get('watched'))}")
    lines.append(f"# TYPE {prefix}_replicas_healthy gauge")
    lines.append(f"{prefix}_replicas_healthy {fmt(quorum.get('healthy'))}")
    lines.append(f"# HELP {prefix}_desired_replicas advisory scaling "
                 f"signal (queue + burn, with hysteresis)")
    lines.append(f"# TYPE {prefix}_desired_replicas gauge")
    lines.append(f"{prefix}_desired_replicas "
                 f"{fmt(signal.get('desired_replicas'))}")
    return "\n".join(lines) + "\n"


class FleetTelemetryServer:
    """The fleet plane's HTTP surface over one :class:`FleetAggregator`
    (module docstring): ``/metrics``, ``/healthz`` (quorum), ``/slo``
    (merged multi-window burn), ``/fleet`` (topology + scaling signal),
    ``/snapshot`` (the MERGED state in the replica wire format — fleet
    views compose). Same lifecycle and handler discipline as the
    per-replica ``LiveTelemetryServer``."""

    def __init__(
        self,
        fleet: FleetAggregator,
        port: int = 0,
        host: str = "127.0.0.1",
        slo_path: Optional[str] = None,
        quorum: float = 0.5,
        topology: Optional[Callable[[], Dict]] = None,
    ):
        if not 0.0 < quorum <= 1.0:
            raise ValueError(f"quorum must be in (0, 1], got {quorum}")
        self.fleet = fleet
        self.quorum = float(quorum)
        self._topology = topology
        self._host = host
        self._want_port = int(port)
        self.slo_path = slo_path
        self._slo = None
        if slo_path is not None:
            from esr_tpu.obs.report import load_slo

            self._slo = load_slo(slo_path)  # fail fast on a broken gate
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- endpoint bodies (pure, testable without sockets) -------------------

    def metrics_page(self) -> str:
        merged = render_prometheus(self.fleet.snapshot(),
                                   prefix="esr_fleet")
        block = fleet_metrics_block(
            self.fleet.replica_table(), self.fleet.scaling_signal(),
            self.fleet.quorum_stats(),
        )
        return merged + block

    def healthz_doc(self) -> Tuple[int, Dict]:
        """Quorum health: 200 while at least ``quorum`` of the watched
        replicas are FRESH and healthy (an empty watch list has no
        quorum to claim)."""
        stats = self.fleet.quorum_stats()
        frac = stats["fraction"]
        ok = frac is not None and frac >= self.quorum
        doc = {
            "healthy": ok,
            "quorum": self.quorum,
            "watched": stats["watched"],
            "fresh": stats["fresh"],
            "healthy_replicas": stats["healthy"],
            "fraction": frac,
            "replicas": {
                rid: {"healthy": row["healthy"], "stale": row["stale"]}
                for rid, row in self.fleet.replica_table().items()
            },
        }
        return (200 if ok else 503), doc

    def slo_doc(self) -> Tuple[int, Dict]:
        """Multi-window burn over MERGED windows — the per-replica
        ``/slo`` contract verbatim (same shared window semantics, same
        verdict mapping), just evaluated on fleet-merged snapshots."""
        if self._slo is None:
            return 404, {"error": "no SLO file configured (slo_path)"}
        from esr_tpu.obs.report import evaluate_slo_window

        fast_s, slow_s = self.fleet.windows
        fast = evaluate_slo_window(
            self.fleet.snapshot(window_s=fast_s), self._slo)
        slow = evaluate_slo_window(
            self.fleet.snapshot(window_s=slow_s), self._slo)
        if not fast["ok"] and not slow["ok"]:
            status, verdict = 503, "page"       # sustained burn
        elif not (fast["ok"] and slow["ok"]):
            status, verdict = 429, "warn"       # spike or recovering
        else:
            status, verdict = 200, "ok"
        return status, {
            "verdict": verdict,
            "slo": self.slo_path,
            "windows_s": [fast_s, slow_s],
            "fast": fast,
            "slow": slow,
        }

    def fleet_doc(self) -> Dict:
        """The topology/autoscaler document: per-replica health + queue
        + staleness, who merged, quorum, the scaling signal, optional
        ring ownership from the router."""
        table = self.fleet.replica_table()
        _st, merged_ids, excluded = self.fleet.merged_state(None)
        doc = {
            "replicas": table,
            "merged": merged_ids,
            "excluded": excluded,
            "quorum": {"threshold": self.quorum,
                       **self.fleet.quorum_stats()},
            "scaling": self.fleet.scaling_signal(),
            "windows_s": list(self.fleet.windows),
        }
        if self._topology is not None:
            try:
                doc["topology"] = self._topology()
            except Exception as e:
                # a router mid-teardown must not take /fleet down with it
                doc["topology"] = {"error": repr(e)}
        return doc

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> Optional[int]:
        return (self._httpd.server_address[1]
                if self._httpd is not None else None)

    def start(self) -> "FleetTelemetryServer":
        if self._httpd is not None:
            return self
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence per-request stderr spam
                pass

            def _send(self, status: int, body: str, ctype: str) -> None:
                payload = body.encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                parts = self.path.split("?", 1)
                path = parts[0].rstrip("/") or "/"
                query = parts[1] if len(parts) > 1 else ""
                try:
                    if path == "/metrics":
                        self._send(
                            200, server.metrics_page(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/healthz":
                        status, doc = server.healthz_doc()
                        self._send(status, json.dumps(doc, indent=2),
                                   "application/json")
                    elif path == "/slo":
                        status, doc = server.slo_doc()
                        self._send(status, json.dumps(doc, indent=2),
                                   "application/json")
                    elif path == "/fleet":
                        self._send(200,
                                   json.dumps(server.fleet_doc(), indent=2),
                                   "application/json")
                    elif path == "/snapshot":
                        try:
                            windows = parse_windows_query(query)
                        except ValueError as e:
                            self._send(400, json.dumps({"error": str(e)}),
                                       "application/json")
                            return
                        if windows is None:
                            windows = server.fleet.windows
                        self._send(
                            200,
                            json.dumps(
                                server.fleet.snapshot_wire(windows)),
                            "application/json",
                        )
                    else:
                        self._send(
                            404,
                            json.dumps({"endpoints": [
                                "/metrics", "/healthz", "/slo", "/fleet",
                                "/snapshot"]}),
                            "application/json",
                        )
                except Exception as e:  # noqa: BLE001 - endpoint must answer
                    self._send(500, json.dumps({"error": repr(e)}),
                               "application/json")

        self._httpd = ThreadingHTTPServer(
            (self._host, self._want_port), Handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True,
            name="obs-fleet-http",
        )
        self._thread.start()
        return self

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class FleetPlane:
    """One running fleet view: aggregator + HTTP server + the optional
    scraper daemon. ``close()`` stops scraper then server (idempotent)."""

    def __init__(self, fleet: FleetAggregator,
                 server: FleetTelemetryServer):
        self.fleet = fleet
        self.server = server
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self.server.port

    def start_scraper(self, interval_s: float = 0.5) -> "FleetPlane":
        """Spawn the pull-mode scraper daemon (production cadence when
        no supervisor feeds :meth:`FleetAggregator.ingest`); idempotent.
        Event + timed join, like every poller in this codebase (CX)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(interval_s):
                self.fleet.scrape_once()

        self._thread = threading.Thread(
            target=_loop, daemon=True, name="obs-fleet-scraper"
        )
        self._thread.start()
        return self

    def stop_scraper(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            if not self._thread.is_alive():
                self._thread = None

    def close(self) -> None:
        self.stop_scraper()
        self.server.close()


def start_fleet_plane(
    replicas: Iterable = (),
    port: int = 0,
    host: str = "127.0.0.1",
    slo_path: Optional[str] = None,
    windows: Tuple[float, float] = (60.0, 300.0),
    rel_err: float = 0.01,
    scrape_budget: int = 3,
    quorum: float = 0.5,
    policy: Optional[ScalingPolicy] = None,
    topology: Optional[Callable[[], Dict]] = None,
    fleet: Optional[FleetAggregator] = None,
    scrape_interval_s: Optional[float] = None,
) -> FleetPlane:
    """The one-call wiring for the fleet view: build (or adopt) a
    :class:`FleetAggregator`, watch every replica's ``/snapshot``
    (``replicas`` are ``serving.Replica``-shaped: ``.replica_id`` +
    ``.url(endpoint)``), serve it, and optionally start the pull-mode
    scraper. The caller owns ``close()`` — put it in the teardown
    ``finally`` next to the router's."""
    if fleet is None:
        fleet = FleetAggregator(
            rel_err=rel_err, windows=windows,
            scrape_budget=scrape_budget, policy=policy,
        )
    for rep in replicas:
        fleet.watch(rep.replica_id, rep.url("snapshot"))
    server = FleetTelemetryServer(
        fleet, port=port, host=host, slo_path=slo_path,
        quorum=quorum, topology=topology,
    ).start()
    plane = FleetPlane(fleet, server)
    if scrape_interval_s is not None:
        plane.start_scraper(scrape_interval_s)
    return plane
