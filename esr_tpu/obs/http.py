"""Dependency-free HTTP exposition for the live telemetry plane (obs v3).

A ``http.server`` thread (stdlib-only, like all of ``esr_tpu.obs``)
serving three endpoints a router, autoscaler, or human can poll while the
run is in flight (docs/OBSERVABILITY.md "The live plane"):

- ``/metrics`` — Prometheus text exposition format v0.0.4: every
  aggregator counter (``*_total``), gauge, span-family sketch (rendered
  as a summary: ``{quantile="0.5"|"0.99"}`` + ``_sum``/``_count``),
  per-class window-latency summary, goodput, and serving totals. Metric
  names are sanitized to ``[a-zA-Z0-9_:]``; label VALUES come only from
  bounded vocabularies (span family, request class) — analysis rule
  ESR013 polices the producer side so per-request names can never reach
  this surface.
- ``/healthz`` — process liveness + component health: every registered
  health source (:func:`register_health_source` — the ``DevicePrefetcher``
  stall watchdog, the serving tier's lane-quarantine ledger) is consulted;
  HTTP 200 when all healthy, 503 when any is not. The body is JSON with
  the per-source detail either way. Source names may carry an ``@<ns>``
  suffix (the fleet tier runs N replicas in one process): a server built
  with ``ns=...`` sees only its own namespaced sources plus the
  un-suffixed process-wide ones, so replica A's quarantine can never 503
  replica B (docs/SERVING.md "The fleet").
- ``/slo`` — LIVE multi-window burn-rate evaluation of the same
  ``configs/slo.yml`` the offline reporter gates on: the rules are
  evaluated against the aggregator's fast-window snapshot AND its
  slow-window snapshot (``windows=(60, 300)`` seconds by default).
  Both windows violating → 503 (page: the error budget is burning at
  sustained rate); exactly one violating → 429 (warn: transient spike or
  recovering); neither → 200. A polling router sheds on 503, eases on
  429 — the VirtualFlow-style fleet signal ROADMAP.md's autoscaler needs.
- ``/snapshot?window_s=`` — the obs v5 WIRE format
  (``aggregate.snapshot_wire``): one versioned JSON document carrying the
  serialized accumulation state (sketch buckets, counters, gauges,
  numerics) cumulative + per requested trailing window, plus this
  replica's health body and its own ``/slo`` verdict. This is the single
  fetch per replica per poll that the fleet plane
  (``obs/fleetview.py``) and the ``ReplicaSupervisor`` both live on.

Strictly opt-in: nothing constructs this server unless
``trainer.live_telemetry`` / ``ServingEngine(live_port=...)`` /
``serve.py --live-port`` asks for it, and it binds loopback by default.
``port=0`` binds an ephemeral port (tests, multi-replica hosts); the
bound port is readable at ``server.port``.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

__all__ = [
    "parse_windows_query",
    "register_health_source",
    "unregister_health_source",
    "health_snapshot",
    "LiveTelemetryServer",
    "LivePlane",
    "start_live_plane",
]


# ---------------------------------------------------------------------------
# health registry: components report liveness without knowing who asks.
# The exact pattern of obs.set_active_sink — process-global, explicit,
# cheap. Each source is a callable returning a dict with at least
# {"healthy": bool}; a raising source reports unhealthy (never raises
# into the endpoint).

_HEALTH_LOCK = threading.Lock()
_HEALTH_SOURCES: Dict[str, Callable[[], Dict]] = {}


def register_health_source(name: str, fn: Callable[[], Dict]) -> None:
    """Register (or replace) a named component health callable."""
    with _HEALTH_LOCK:
        _HEALTH_SOURCES[name] = fn


def unregister_health_source(name: str) -> None:
    with _HEALTH_LOCK:
        _HEALTH_SOURCES.pop(name, None)


def health_snapshot(ns: Optional[str] = None) -> Tuple[bool, Dict[str, Dict]]:
    """``(all_healthy, {source: detail})`` over every registered source.

    ``ns`` scopes the view for MULTI-REPLICA processes (the fleet tier,
    docs/SERVING.md "The fleet"): source names may carry an ``@<ns>``
    suffix (``serving_lanes@r0``), and a namespaced snapshot sees only
    its own ``@<ns>`` sources plus the un-suffixed process-wide ones —
    replica A's lane quarantine must never flip replica B's ``/healthz``
    to 503 (the router would drain a healthy replica). ``ns=None`` (the
    default, every single-replica process) keeps today's behavior: every
    source, namespaced or not."""
    with _HEALTH_LOCK:
        sources = dict(_HEALTH_SOURCES)
    if ns is not None:
        suffix = "@" + str(ns)
        sources = {
            name: fn for name, fn in sources.items()
            if "@" not in name or name.endswith(suffix)
        }
    out: Dict[str, Dict] = {}
    healthy = True
    for name in sorted(sources):
        try:
            detail = dict(sources[name]())
        except Exception as e:  # esr: noqa(ESR012)
            # not silent: the failure IS the health signal — it surfaces
            # as {"healthy": false, "error": ...} in the /healthz body
            # and flips the endpoint to 503
            detail = {"healthy": False, "error": repr(e)}
        detail.setdefault("healthy", True)
        out[name] = detail
        healthy = healthy and bool(detail["healthy"])
    return healthy, out


# ---------------------------------------------------------------------------
# Prometheus text exposition (v0.0.4)

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _pname(name: str) -> str:
    out = _NAME_SANITIZE.sub("_", str(name))
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _label(value) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n"
    )


def _fmt(v) -> str:
    if v is None:
        return "NaN"
    try:
        return repr(float(v))
    except (TypeError, ValueError):
        return "NaN"


def render_prometheus(snapshot: Dict, prefix: str = "esr") -> str:
    """An aggregator snapshot (``LiveAggregator.snapshot()``) → the
    Prometheus v0.0.4 text page. Pure function — pinned parseable by
    ``tests/test_obs_live.py``."""
    lines = []

    def emit(name, kind, samples, help_=None):
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")
        for labels, value in samples:
            if labels:
                body = ",".join(
                    f'{k}="{_label(v)}"' for k, v in labels.items()
                )
                lines.append(f"{name}{{{body}}} {_fmt(value)}")
            else:
                lines.append(f"{name} {_fmt(value)}")

    emit(f"{prefix}_records_total", "counter",
         [({}, snapshot.get("records", 0))],
         "telemetry records observed by the live aggregator")
    for name, total in snapshot.get("counters", {}).items():
        emit(f"{prefix}_{_pname(name)}_total", "counter", [({}, total)])
    for name, value in snapshot.get("gauges", {}).items():
        emit(f"{prefix}_{_pname(name)}", "gauge", [({}, value)])
    events = snapshot.get("events", {})
    if events:
        emit(f"{prefix}_event_total", "counter",
             [({"event": k}, v) for k, v in sorted(events.items())])
    goodput = snapshot.get("goodput", {})
    emit(f"{prefix}_goodput", "gauge", [({}, goodput.get("value"))],
         "live goodput (attribution-weighted or chunk busy/wall)")
    serving = snapshot.get("serving", {})
    if serving:
        for key in ("requests", "completed", "errors", "windows",
                    "preemptions"):
            emit(f"{prefix}_serving_{key}_total", "counter",
                 [({}, serving.get(key, 0))])
    # span-family sketches as summaries: bounded label vocabulary (span
    # family names are static in the codebase — ESR013)
    spans = snapshot.get("spans", {})
    if spans:
        name = f"{prefix}_span_seconds"
        lines.append(f"# TYPE {name} summary")
        for fam, rec in sorted(spans.items()):
            for q, key in ((0.5, "p50_ms"), (0.99, "p99_ms")):
                v = rec.get(key)
                v = None if v is None else v / 1e3
                lines.append(
                    f'{name}{{span="{_label(fam)}",quantile="{q}"}} '
                    f"{_fmt(v)}"
                )
            lines.append(
                f'{name}_sum{{span="{_label(fam)}"}} '
                f"{_fmt(rec.get('total_s'))}"
            )
            lines.append(
                f'{name}_count{{span="{_label(fam)}"}} '
                f"{_fmt(rec.get('count'))}"
            )
    # the numerics plane (obs v4): bounded tag vocabulary (the static
    # probe catalog — ESR013-safe), worst-case per-tag readings
    num = snapshot.get("numerics", {}) or {}
    if num.get("tags"):
        emit(f"{prefix}_numerics_finite_frac", "gauge",
             [({}, num.get("finite_frac"))],
             "worst per-tag finite fraction across the probed tensors")
        emit(f"{prefix}_numerics_nonfinite_total", "counter",
             [({"tag": t}, rec.get("nonfinite"))
              for t, rec in sorted(num["tags"].items())])
        for key in ("max_abs", "finite_frac", "underflow_frac",
                    "overflow_frac"):
            emit(f"{prefix}_numerics_tag_{key}", "gauge",
                 [({"tag": t}, rec.get(key))
                  for t, rec in sorted(num["tags"].items())])
    classes = serving.get("classes", {}) if serving else {}
    if classes:
        name = f"{prefix}_serving_window_latency_seconds"
        lines.append(f"# TYPE {name} summary")
        for cls, rec in sorted(classes.items()):
            for q, key in ((0.5, "window_latency_p50_ms"),
                           (0.99, "window_latency_p99_ms")):
                v = rec.get(key)
                v = None if v is None else v / 1e3
                lines.append(
                    f'{name}{{cls="{_label(cls)}",quantile="{q}"}} '
                    f"{_fmt(v)}"
                )
            lines.append(
                f'{name}_count{{cls="{_label(cls)}"}} '
                f"{_fmt(rec.get('windows'))}"
            )
    return "\n".join(lines) + "\n"


def parse_windows_query(query: str) -> Optional[Tuple[float, ...]]:
    """``window_s=60`` / ``window_s=60,300`` → the explicit trailing
    windows a ``/snapshot`` request asks for; absent/empty → ``None``
    (the server substitutes its burn-rate pair). Raises ``ValueError``
    on junk — the endpoint answers 400, never a torn document."""
    raw = parse_qs(query).get("window_s")
    if not raw:
        return None
    try:
        windows = tuple(
            float(tok) for part in raw for tok in part.split(",") if tok
        )
    except ValueError:
        raise ValueError(
            f"window_s must be comma-separated seconds, got {raw!r}"
        ) from None
    if any(w <= 0 for w in windows):
        raise ValueError(f"window_s values must be > 0, got {raw!r}")
    return windows or None


# ---------------------------------------------------------------------------
# the server


class LiveTelemetryServer:
    """The live plane's HTTP surface over one :class:`LiveAggregator`
    (module docstring). ``start()`` binds and serves on a daemon thread;
    ``close()`` shuts down. Never traces, never touches jax."""

    def __init__(
        self,
        aggregator,
        port: int = 0,
        host: str = "127.0.0.1",
        slo_path: Optional[str] = None,
        windows: Tuple[float, float] = (60.0, 300.0),
        ns: Optional[str] = None,
    ):
        self.aggregator = aggregator
        # health-source namespace (fleet tier): /healthz consults only
        # this server's @<ns> sources + the un-suffixed global ones
        self.ns = ns
        self._host = host
        self._want_port = int(port)
        self.slo_path = slo_path
        self._slo = None
        if slo_path is not None:
            from esr_tpu.obs.report import load_slo

            self._slo = load_slo(slo_path)  # fail fast on a broken gate
        if not (len(windows) == 2 and 0 < windows[0] <= windows[1]):
            raise ValueError(
                f"windows must be (fast_s, slow_s) with 0 < fast <= slow, "
                f"got {windows!r}"
            )
        self.windows = (float(windows[0]), float(windows[1]))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- endpoint bodies (pure, testable without sockets) -------------------

    def metrics_page(self) -> str:
        return render_prometheus(self.aggregator.snapshot())

    def healthz_doc(self) -> Tuple[int, Dict]:
        healthy, sources = health_snapshot(ns=self.ns)
        snap = self.aggregator.snapshot()
        doc = {
            "healthy": healthy,
            "uptime_s": snap.get("uptime_s"),
            "records": snap.get("records"),
            "sources": sources,
        }
        return (200 if healthy else 503), doc

    def _eval_window(self, window_s: float) -> Dict:
        """One window's burn verdict — delegated to the SHARED windowed
        semantics (:func:`esr_tpu.obs.report.evaluate_slo_window`: empty
        window = no data; metric absent from the window = skipped as
        missing, not violated; present-but-non-finite still violates) so
        this endpoint and the fleet plane's merged-window evaluation can
        never diverge."""
        from esr_tpu.obs.report import evaluate_slo_window

        return evaluate_slo_window(
            self.aggregator.snapshot(window_s=window_s), self._slo
        )

    def slo_doc(self) -> Tuple[int, Dict]:
        if self._slo is None:
            return 404, {"error": "no SLO file configured (--live-slo / "
                                  "slo_path)"}
        fast_s, slow_s = self.windows
        fast = self._eval_window(fast_s)
        slow = self._eval_window(slow_s)
        if not fast["ok"] and not slow["ok"]:
            status, verdict = 503, "page"       # sustained burn
        elif not (fast["ok"] and slow["ok"]):
            status, verdict = 429, "warn"       # spike or recovering
        else:
            status, verdict = 200, "ok"
        return status, {
            "verdict": verdict,
            "slo": self.slo_path,
            "windows_s": [fast_s, slow_s],
            "fast": fast,
            "slow": slow,
        }

    def snapshot_doc(self, windows: Optional[Tuple[float, ...]] = None
                     ) -> Dict:
        """The ``/snapshot`` body (obs v5): ONE document carrying
        everything a fleet consumer needs per poll — the versioned wire
        state (cumulative + the requested trailing windows, defaulting
        to this server's burn-rate pair), this replica's health body,
        and its own ``/slo`` verdict — so death detection and the fleet
        merge ride a single HTTP fetch per replica per poll
        (docs/SERVING.md "The fleet signal")."""
        if windows is None:
            windows = self.windows
        doc = self.aggregator.snapshot_wire(windows=windows)
        doc["replica"] = self.ns
        healthy, sources = health_snapshot(ns=self.ns)
        doc["health"] = {"healthy": healthy, "sources": sources}
        doc["slo_verdict"] = (None if self._slo is None
                              else self.slo_doc()[1]["verdict"])
        return doc

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> Optional[int]:
        return (self._httpd.server_address[1]
                if self._httpd is not None else None)

    def start(self) -> "LiveTelemetryServer":
        if self._httpd is not None:
            return self
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence per-request stderr spam
                pass

            def _send(self, status: int, body: str, ctype: str) -> None:
                payload = body.encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                parts = urlsplit(self.path)
                path = parts.path.rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        self._send(
                            200, server.metrics_page(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/healthz":
                        status, doc = server.healthz_doc()
                        self._send(status, json.dumps(doc, indent=2),
                                   "application/json")
                    elif path == "/slo":
                        status, doc = server.slo_doc()
                        self._send(status, json.dumps(doc, indent=2),
                                   "application/json")
                    elif path == "/snapshot":
                        try:
                            windows = parse_windows_query(parts.query)
                        except ValueError as e:
                            self._send(400, json.dumps({"error": str(e)}),
                                       "application/json")
                            return
                        self._send(200,
                                   json.dumps(server.snapshot_doc(windows)),
                                   "application/json")
                    else:
                        self._send(
                            404,
                            json.dumps({"endpoints": [
                                "/metrics", "/healthz", "/slo",
                                "/snapshot"]}),
                            "application/json",
                        )
                except Exception as e:  # noqa: BLE001 - endpoint must answer
                    self._send(500, json.dumps({"error": repr(e)}),
                               "application/json")

        self._httpd = ThreadingHTTPServer(
            (self._host, self._want_port), Handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True,
            name="obs-live-http",
        )
        self._thread.start()
        return self

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class LivePlane:
    """One attached live plane: aggregator tapped into a sink + the HTTP
    server over it. ``close()`` detaches and shuts down (idempotent)."""

    def __init__(self, sink, aggregator, server: LiveTelemetryServer):
        self.sink = sink
        self.aggregator = aggregator
        self.server = server

    @property
    def port(self) -> Optional[int]:
        return self.server.port

    def close(self) -> None:
        self.server.close()
        if self.sink is not None:
            name = ("numerics" if self.server.ns is None
                    else f"numerics@{self.server.ns}")
            unregister_health_source(name)
            self.aggregator.detach(self.sink)
            self.sink = None


def start_live_plane(
    sink,
    port: int = 0,
    host: str = "127.0.0.1",
    slo_path: Optional[str] = None,
    windows: Tuple[float, float] = (60.0, 300.0),
    rel_err: float = 0.01,
    ns: Optional[str] = None,
) -> LivePlane:
    """The one-call wiring every entry point uses: build a
    :class:`~esr_tpu.obs.aggregate.LiveAggregator`, attach it to ``sink``,
    and serve it. The caller owns ``close()`` (put it in the teardown
    ``finally`` next to the sink's)."""
    from esr_tpu.obs.aggregate import LiveAggregator

    if sink is None:
        raise ValueError(
            "live telemetry requires an active TelemetrySink (the live "
            "plane runs BESIDE the JSONL stream, never instead of it — "
            "docs/OBSERVABILITY.md)"
        )
    aggregator = LiveAggregator(rel_err=rel_err).attach(sink)
    # the numerics plane's component health (obs v4): /healthz flips to
    # 503 the moment any probed tag reports non-finite elements — the
    # value-telemetry dual of the prefetcher stall / lane-quarantine
    # sources. Registered for EVERY live plane (trainer and serving
    # tier alike); healthy while no probes report.
    from esr_tpu.obs.numerics import numerics_health_source

    register_health_source(
        "numerics" if ns is None else f"numerics@{ns}",
        numerics_health_source(aggregator),
    )
    server = LiveTelemetryServer(
        aggregator, port=port, host=host, slo_path=slo_path,
        windows=windows, ns=ns,
    ).start()
    return LivePlane(sink, aggregator, server)
