"""Device-side visibility for the live plane (obs v3).

Two producers that make on-chip state first-class telemetry instead of
ad-hoc script output (docs/OBSERVABILITY.md "Device-side visibility"):

- :class:`DeviceWatermark` — a polling thread emitting
  ``device_mem_bytes_in_use`` / ``device_mem_peak_bytes`` gauges from
  ``device.memory_stats()`` into the active sink (and, through the sink's
  observer tap, the live aggregator — so ``/metrics`` exposes HBM
  occupancy while a run is in flight). **None-tolerant on CPU**: backends
  without memory stats poll once, observe the ``None``, emit a single
  ``device_watermark_unavailable`` event, and stop — zero recurring cost
  where the signal does not exist.
- :class:`ProfilerCapture` — the ``--profile-steps N`` knob's body: wraps
  ``jax.profiler.start_trace``/``stop_trace`` around the next ``N``
  steps/chunks of the trainer or serving loop and stamps a
  ``profiler_capture`` telemetry event carrying the artifact directory,
  so an on-chip capture is a durable, discoverable record in the run's
  evidence stream (the r5 verdict's missing captures were exactly this
  kind of script-local state).

Contract notes (the sink's rules apply): ``jax`` is imported lazily and
only AFTER probing ``backends_are_initialized`` — these helpers are
started by entry points that have already made backend contact, but must
stay wedge-proof if constructed earlier; every failure path degrades to a
warning + telemetry event, never an exception into the hot loop.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from esr_tpu.obs.sink import active_sink

logger = logging.getLogger(__name__)

__all__ = ["DeviceWatermark", "ProfilerCapture", "device_memory_stats"]


def device_memory_stats(device_index: int = 0) -> Optional[Dict]:
    """``jax.devices()[i].memory_stats()`` behind the wedge-proof probe:
    returns None when no backend is initialized, the platform reports no
    stats (CPU), or anything raises. Never initializes a backend."""
    try:
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return None
        import jax

        devs = jax.devices()
        if not devs or device_index >= len(devs):
            return None
        stats = devs[device_index].memory_stats()
        return dict(stats) if stats else None
    except Exception:  # noqa: BLE001 - visibility is best-effort by contract
        return None


class DeviceWatermark:
    """Poll device memory stats into the telemetry stream (module
    docstring). ``start()`` spawns a daemon thread; ``stop()`` joins it.
    ``poll_once()`` is the testable body."""

    def __init__(self, sink=None, interval_s: float = 1.0,
                 device_index: int = 0):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self._sink = sink
        self.interval_s = float(interval_s)
        self.device_index = int(device_index)
        self.polls = 0
        self.peak_bytes = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reported_unavailable = False

    def _sink_now(self):
        return self._sink if self._sink is not None else active_sink()

    def poll_once(self) -> Optional[Dict]:
        """One poll: emit the gauges when stats exist; on the first
        stat-less poll emit ``device_watermark_unavailable`` (once) and
        return None — the caller (or the thread loop) stops polling."""
        self.polls += 1
        stats = device_memory_stats(self.device_index)
        sink = self._sink_now()
        if stats is None:
            if sink is not None and not self._reported_unavailable:
                self._reported_unavailable = True
                sink.event(
                    "device_watermark_unavailable",
                    device_index=self.device_index,
                )
            return None
        in_use = int(stats.get("bytes_in_use", 0) or 0)
        peak = int(
            stats.get("peak_bytes_in_use", 0) or 0
        ) or max(self.peak_bytes, in_use)
        self.peak_bytes = max(self.peak_bytes, peak, in_use)
        if sink is not None:
            sink.gauge(
                "device_mem_bytes_in_use", in_use,
                device_index=self.device_index,
            )
            sink.gauge(
                "device_mem_peak_bytes", self.peak_bytes,
                device_index=self.device_index,
                limit_bytes=stats.get("bytes_limit"),
            )
        return {"bytes_in_use": in_use, "peak_bytes": self.peak_bytes,
                "bytes_limit": stats.get("bytes_limit")}

    def _run(self, trace_ctx=None) -> None:
        # adopt the starter's trace context (obs/trace.py): contextvars do
        # not flow into threads, and without this every watermark gauge
        # parked outside the run's causal tree (CX005 — the concurrency
        # auditor's first real catch)
        from esr_tpu.obs import trace

        with trace.adopt(trace_ctx):
            while not self._stop.is_set():
                if self.poll_once() is None:
                    return  # no stats on this backend: stop, loudly (event)
                self._stop.wait(self.interval_s)

    def start(self) -> "DeviceWatermark":
        if self._thread is not None and not self._thread.is_alive():
            # a handle retained by a timed-out stop() whose zombie has
            # SINCE exited: drop it, or start() would be a no-op forever
            # (the dead-poller bug class all over again)
            self._thread = None
        if self._thread is None:
            # a watermark restarted after stop() must poll again: the stop
            # event persists across start/stop cycles, and a set flag made
            # the fresh thread exit on its first lap — a silently dead
            # poller (caught by the CX sweep's DeviceWatermark audit,
            # pinned by tests/test_concurrency_audit.py). Safe to clear
            # here ONLY because stop() keeps the handle while a wedged
            # poller is still alive, so this branch is unreachable then.
            self._stop.clear()
            from esr_tpu.obs import trace

            self._thread = threading.Thread(
                target=self._run, args=(trace.capture(),),
                daemon=True, name="device-watermark",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, 2 * self.interval_s))
            if self._thread.is_alive():
                # a poller wedged inside memory_stats outlived the join:
                # KEEP the handle so a later start() cannot clear the
                # stop flag and resurrect it as a duplicate — start()
                # stays a no-op until the zombie actually dies
                return
            self._thread = None


class ProfilerCapture:
    """Bounded on-chip profiler capture: trace the next ``steps``
    steps/chunks, then stop and stamp a ``profiler_capture`` event with
    the artifact directory (module docstring).

    Drive it from a host loop: ``maybe_start()`` before the loop,
    ``step(n)`` after each super-step/chunk (stops itself at the budget),
    ``stop()`` in the teardown ``finally`` (idempotent — covers loops
    shorter than the budget). All failure paths log + stamp the event
    with ``error`` instead of raising: a broken profiler must not take
    the run down."""

    def __init__(self, trace_dir: str, steps: int, sink=None,
                 site: str = "train"):
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        self.trace_dir = trace_dir
        self.steps = int(steps)
        self.site = site
        self._sink = sink
        self.steps_covered = 0
        self._active = False
        self._done = False
        self._error: Optional[str] = None

    def maybe_start(self) -> bool:
        if self._active or self._done:
            return self._active
        try:
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self._active = True
        except Exception as e:  # noqa: BLE001 - capture is best-effort
            self._error = repr(e)
            self._done = True
            logger.warning(
                "profiler capture failed to start (%s): %r",
                self.trace_dir, e,
            )
            self._emit()
        return self._active

    def step(self, n: int = 1) -> None:
        if not self._active:
            return
        self.steps_covered += int(n)
        if self.steps_covered >= self.steps:
            self.stop()

    def stop(self) -> None:
        if not self._active:
            return
        self._active = False
        self._done = True
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 - capture is best-effort
            self._error = repr(e)
            logger.warning("profiler capture failed to stop: %r", e)
        self._emit()

    def _emit(self) -> None:
        sink = self._sink if self._sink is not None else active_sink()
        if sink is None:
            return
        sink.event(
            "profiler_capture",
            dir=self.trace_dir,
            steps=self.steps,
            steps_covered=self.steps_covered,
            site=self.site,
            ok=self._error is None,
            error=self._error,
        )
