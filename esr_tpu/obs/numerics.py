"""The numerics plane, host side (obs v4 — docs/OBSERVABILITY.md).

Obs v1–v3 observe *time* (spans, traces, live sketches); this module is
their dual for *values*: it consumes the on-device tensor-statistics
probes (``esr_tpu.ops.numerics`` — the jnp half that rides the traced
programs) and turns them into

- ``numerics`` JSONL records (one per tag at the trainer's existing
  ``train_log_step`` cadence — the cadence-gated readback stays the only
  host sync);
- a shared live/offline rollup section (:func:`rollup`) in the
  reporter's dotted namespace, so ``configs/slo.yml`` can gate on
  ``numerics.finite_frac`` identically against a finished telemetry
  file and a live ``/slo`` window (the v3 parity contract);
- layer-named anomaly attribution (:func:`first_offending_tag`) — the
  AnomalyGuard's rollback events carry the first model seam whose
  activations went non-finite instead of just "nan_loss";
- the precision-drift attribution harness (:func:`run_drift`, CLI
  ``python -m esr_tpu.obs drift``): one seeded batch through an
  f32-reference and a candidate-dtype twin of the same model, diffed
  per probe tag, naming the first layer exceeding tolerance.

Module-level imports stay stdlib+numpy-free of jax (the obs contract:
importable from the NumPy-only data layer and accelerator-free CI
hosts); jax enters only lazily inside the drift harness.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# mirror of esr_tpu.ops.numerics.STAT_FIELDS/REDUCE_KINDS, duplicated so
# this module never imports the jnp half at module scope; pinned equal by
# tests/test_obs_numerics.py
STAT_FIELDS = (
    "rms", "max_abs", "mean", "nonfinite", "underflow", "overflow", "count",
)
REDUCE_KINDS = ("max", "max", "last", "sum", "max", "max", "sum")
NSTATS = len(STAT_FIELDS)

# the probe-tag catalog in MODEL ORDER (docs/OBSERVABILITY.md "The
# numerics plane"): input-to-output through DeepRecurrNet's seams, then
# the training-side taps. "First offending tag" resolution walks this
# order, so the named layer is the EARLIEST seam the anomaly reached —
# the causal head of the poison, not a downstream symptom.
TAG_ORDER = (
    "head_out",
    "enc0", "enc1", "enc2",
    "gru_fwd", "gru_bwd",
    "dcn_offsets", "dcn_mask", "dcn_out",
    "dec0", "dec1", "dec2",
    "tail_out",
    "loss", "grad_norm",
)


def order_tags(tags) -> List[str]:
    """``tags`` sorted in catalog order; unknown tags (future models)
    follow alphabetically after the known catalog."""
    known = {t: i for i, t in enumerate(TAG_ORDER)}
    return sorted(tags, key=lambda t: (known.get(t, len(TAG_ORDER)), t))


# ---------------------------------------------------------------------------
# readback: stats vectors (numpy) -> merged per-tag vectors -> record fields


def merge_host(acc, new):
    """NumPy twin of ``ops.numerics.merge_stat_vectors`` (pinned equal in
    tests): accumulate one stats vector into another under the per-field
    reduce law (max for extrema, sum for counts, last for ``mean``)."""
    import numpy as np

    acc = np.asarray(acc, np.float32)
    new = np.asarray(new, np.float32)
    out = np.where(
        [k == "max" for k in REDUCE_KINDS],
        np.maximum(acc, new),
        np.where([k == "sum" for k in REDUCE_KINDS], acc + new, new),
    )
    return out.astype(np.float32)


def merge_readback(numerics) -> Dict[str, "object"]:
    """Collapse a super-step's numerics readback to one vector per tag.

    Accepts either the fused super-step form — ``{tag: [k, NSTATS]}``
    (``lax.scan`` stacks the k chained steps' vectors) — or the
    single-step-list form the epoch tail produces (``[{tag: [NSTATS]},
    ...]``). Host-side numpy only; runs inside the trainer's existing
    cadence-gated readback."""
    import numpy as np

    if isinstance(numerics, (list, tuple)):
        merged: Dict[str, object] = {}
        for entry in numerics:
            for tag, vec in entry.items():
                vec = np.asarray(vec, np.float32)
                merged[tag] = (
                    vec if tag not in merged else merge_host(merged[tag], vec)
                )
        return merged
    out: Dict[str, object] = {}
    for tag, stacked in numerics.items():
        arr = np.asarray(stacked, np.float32)
        if arr.ndim == 1:
            out[tag] = arr
            continue
        acc = arr[0]
        for row in arr[1:]:
            acc = merge_host(acc, row)
        out[tag] = acc
    return out


def finite_frac(nonfinite: float, count: float) -> Optional[float]:
    """THE finite-fraction convention of the whole plane (records, the
    offline report, the live snapshot, /healthz, the SLO rule): ``None``
    with no data, and NEVER exactly 1.0 while any non-finite element was
    counted — plain ``round(1 - tiny/huge, 6)`` rounds back up to 1.0
    and would pass the ``min: 1.0`` SLO gate with NaNs present."""
    if count <= 0:
        return None
    if nonfinite <= 0:
        return 1.0
    return min(round(1.0 - nonfinite / count, 6), 0.999999)


def stats_fields(vec) -> Dict[str, float]:
    """One merged stats vector -> the JSONL record payload (field names
    from :data:`STAT_FIELDS` plus the derived ``finite_frac``)."""
    import numpy as np

    vec = np.asarray(vec, np.float64)
    fields = {name: round(float(v), 6) for name, v in zip(STAT_FIELDS, vec)}
    fields["finite_frac"] = finite_frac(
        fields["nonfinite"], fields["count"]
    )
    return fields


def first_offending_tag(numerics: Optional[Dict]) -> Optional[str]:
    """The earliest catalog tag whose merged stats carry non-finite
    elements — the layer-named attribution the AnomalyGuard stamps onto
    ``recovery_skip_step`` / ``recovery_rollback`` events. ``None`` when
    no probes are present or every tag is clean (the guard then falls
    back to the plain "nan_loss" story)."""
    import numpy as np

    if not numerics:
        return None
    idx = STAT_FIELDS.index("nonfinite")
    for tag in order_tags(numerics):
        vec = np.asarray(numerics[tag], np.float64)
        if vec.shape[-1] == NSTATS and float(vec[idx]) > 0:
            return tag
    return None


def poison_tag(numerics: Dict, tag: str = "loss") -> Dict:
    """Enact an injected ``nan_loss`` fault on the numerics readback:
    mark every probed element of ``tag`` non-finite, exactly where the
    fault plane poisons the loss scalars (trainer ``consume``) — so the
    chaos gate's layer-named rollback works for simulated faults too."""
    import numpy as np

    out = dict(numerics)
    vec = np.array(
        out.get(tag, np.zeros(NSTATS, np.float32)), np.float32, copy=True
    )
    count = max(float(vec[STAT_FIELDS.index("count")]), 1.0)
    vec[STAT_FIELDS.index("count")] = count
    vec[STAT_FIELDS.index("nonfinite")] = count
    out[tag] = vec
    return out


# ---------------------------------------------------------------------------
# the shared live/offline rollup: per-tag accumulation states -> section.
# Both the offline reporter (obs/report.py) and the LiveAggregator
# (obs/aggregate.py) keep `{tag: state-dict}` tables and feed every
# `numerics` record through ingest(); rollup() renders the one section
# shape both expose, so a single SLO YAML evaluates either view
# (the obs v3 live/offline parity contract).


def new_tag_state() -> Dict[str, float]:
    return {
        "records": 0,
        "rms": 0.0,
        "max_abs": 0.0,
        "nonfinite": 0.0,
        "count": 0.0,
        "underflow": 0.0,
        "overflow": 0.0,
    }


def ingest(states: Dict[str, Dict], rec: Dict) -> None:
    """Fold one ``numerics`` record (as written by ``sink.numerics``)
    into a per-tag state table. Extrema keep their max, counts sum —
    the same law as the on-device accumulation."""
    tag = rec.get("name", "?")
    st = states.get(tag)
    if st is None:
        st = states[tag] = new_tag_state()
    st["records"] += 1
    for key in ("rms", "max_abs", "underflow", "overflow"):
        try:
            st[key] = max(st[key], float(rec.get(key, 0.0) or 0.0))
        except (TypeError, ValueError):
            pass
    for key in ("nonfinite", "count"):
        try:
            st[key] += float(rec.get(key, 0.0) or 0.0)
        except (TypeError, ValueError):
            pass


def merge_states(dst: Dict[str, Dict], src: Dict[str, Dict]) -> None:
    """Merge one state table into another (the live plane's epoch-ring
    merge) — same per-field law as :func:`ingest`."""
    for tag, st in src.items():
        mine = dst.get(tag)
        if mine is None:
            dst[tag] = dict(st)
            continue
        mine["records"] += st["records"]
        for key in ("rms", "max_abs", "underflow", "overflow"):
            mine[key] = max(mine[key], st[key])
        for key in ("nonfinite", "count"):
            mine[key] += st[key]


def rollup(states: Dict[str, Dict]) -> Dict:
    """The report/snapshot ``numerics`` section: per-tag worst-case
    readings plus the headline ``finite_frac`` (the worst tag's) the
    shipped SLO rule gates on. Always present; empty-but-typed when the
    run carried no probes (``finite_frac: None`` + ``allow_missing``)."""
    tags_out = {}
    worst_tag = None
    worst_frac = None
    nonfinite_total = 0.0
    for tag in order_tags(states):
        st = states[tag]
        frac = finite_frac(st["nonfinite"], st["count"])
        tags_out[tag] = {
            "records": st["records"],
            "rms": round(st["rms"], 6),
            "max_abs": round(st["max_abs"], 6),
            "nonfinite": st["nonfinite"],
            "count": st["count"],
            "finite_frac": frac,
            "underflow_frac": round(st["underflow"], 6),
            "overflow_frac": round(st["overflow"], 6),
        }
        nonfinite_total += st["nonfinite"]
        if frac is not None and (worst_frac is None or frac < worst_frac):
            worst_frac, worst_tag = frac, tag
    return {
        "records": sum(st["records"] for st in states.values()),
        "finite_frac": worst_frac,
        "worst_tag": worst_tag,
        "nonfinite_total": nonfinite_total,
        "tags": tags_out,
    }


def numerics_health_source(aggregator):
    """A ``/healthz`` component source over a live aggregator: healthy
    while every probed tag stays fully finite (or no probes have
    reported). Registered by ``obs.http.start_live_plane`` so both the
    trainer's and the serving tier's live planes expose it."""

    def source() -> Dict:
        num = aggregator.snapshot().get("numerics", {}) or {}
        frac = num.get("finite_frac")
        return {
            "healthy": frac is None or frac >= 1.0,
            "finite_frac": frac,
            "worst_tag": num.get("worst_tag"),
            "tags": len(num.get("tags", {})),
        }

    return source


# ---------------------------------------------------------------------------
# the precision-drift attribution harness (`python -m esr_tpu.obs drift`)


def _rel_error(ref, cand) -> float:
    """Norm-relative error between a reference tap and its candidate
    twin: ``||ref - cand|| / (||ref|| + eps)`` in f64, max over the
    tap's firings (raw-mode taps are tuples — one entry per sow)."""
    import numpy as np

    refs = ref if isinstance(ref, (tuple, list)) else (ref,)
    cands = cand if isinstance(cand, (tuple, list)) else (cand,)
    worst = 0.0
    for a, b in zip(refs, cands):
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        denom = float(np.linalg.norm(a.ravel())) + 1e-12
        worst = max(
            worst, float(np.linalg.norm((a - b).ravel())) / denom
        )
    return worst


def run_drift(
    dtype: str = "bfloat16",
    basech: int = 8,
    hw: int = 32,
    frames: int = 3,
    batch: int = 1,
    seed: int = 0,
    tolerance: float = 0.25,
    break_tag: Optional[str] = None,
    inch: int = 2,
) -> Dict:
    """Run one seeded batch through an f32-reference and a
    candidate-dtype twin of the SAME probed model, diff the raw taps per
    tag, and emit the per-layer rel-error ladder naming the first seam
    exceeding ``tolerance``.

    ``break_tag`` arms the seeded precision-breaking fixture
    (``ops.numerics.numerics_breaker`` — a ``(x+256)-256`` cancellation
    executed in each twin's own dtype): exact-ish in f32, destructive in
    bf16, so the harness must finger exactly that layer — the tier-1
    acceptance check for the whole attribution path. The breaker runs in
    the tagged tensor's OWN compute dtype, so a seam that stays f32 even
    in the candidate twin (the decoder scales — the upsample path
    upcasts) honestly does not drift: attribution reflects where reduced
    precision actually reaches.

    Device-free of any accelerator assumption (CPU tier-1 runs it); the
    candidate twin casts params, inputs, and recurrent states to
    ``dtype`` so every conv/matmul executes at the candidate width,
    mirroring how ``trainer.precision: bf16`` casts for the apply.

    ``dtype="int8"`` selects the PTQ serving rung instead: nothing is
    cast — the same f32 feed reruns under ``config.quantize.int8_scope``
    so each contraction quantizes w8a8 with an i32 accumulator exactly as
    serving does, and the ladder attributes per-layer QUANTIZATION error
    (``worst_tag`` names the worst-quantized seam).
    """
    import jax
    import jax.numpy as jnp

    from esr_tpu.config.precision import canonical_dtype
    from esr_tpu.models.esr import DeepRecurrNet
    from esr_tpu.ops.numerics import flatten_probes

    # accept the config spellings ("bf16") next to the numpy names
    # ("bfloat16") — jnp.dtype alone rejects the former with exit 2
    cand_dtype = jnp.dtype(canonical_dtype(dtype))
    model = DeepRecurrNet(
        inch=inch, basech=basech, num_frame=frames,
        numerics=True, numerics_mode="raw", numerics_break=break_tag,
    )
    x = jax.random.normal(
        jax.random.PRNGKey(seed), (batch, frames, hw, hw, inch),
        jnp.float32,
    )
    states = model.init_states(batch, hw, hw)
    variables = model.init(jax.random.PRNGKey(seed + 1), x, states)
    params = {"params": variables["params"]}

    def taps(p, xx, ss):
        (_out, _st), mut = model.apply(
            p, xx, ss, train=False, mutable=["numerics"]
        )
        return mut["numerics"]

    ref = flatten_probes(jax.device_get(taps(params, x, states)))

    if cand_dtype == jnp.dtype(jnp.int8):
        # the int8 PTQ rung does NOT cast anything — params/inputs/states
        # stay f32 and the contraction seams quantize in-graph
        # (esr_tpu.config.quantize). The candidate twin is therefore the
        # SAME f32 feed run under the int8 scope, so the ladder attributes
        # pure quantization error per layer.
        from esr_tpu.config.quantize import int8_scope

        with int8_scope():
            cand = flatten_probes(jax.device_get(taps(params, x, states)))
    else:
        def cast(tree):
            return jax.tree.map(lambda a: a.astype(cand_dtype), tree)

        cand = flatten_probes(jax.device_get(
            taps(cast(params), x.astype(cand_dtype), cast(states))
        ))

    ladder = []
    first = None
    worst_tag = None
    worst_rel = -1.0
    for tag in order_tags(ref):
        rel = _rel_error(ref[tag], cand[tag])
        exceeds = rel > tolerance
        ladder.append({
            "tag": tag,
            "rel_err": round(rel, 6),
            "exceeds": exceeds,
        })
        if exceeds and first is None:
            first = tag
        if rel > worst_rel:
            worst_rel = rel
            worst_tag = tag
    return {
        "dtype": str(cand_dtype),
        "reference": "float32",
        "tolerance": tolerance,
        "seed": seed,
        "model": {
            "name": "DeepRecurrNet", "basech": basech, "hw": hw,
            "frames": frames, "batch": batch, "inch": inch,
        },
        "break_tag": break_tag,
        "first_offender": first,
        # the max-rel-err seam even when nothing exceeds tolerance — the
        # int8 rung's "which layer quantizes worst" attribution reads this
        "worst_tag": worst_tag,
        "n_exceeding": sum(1 for e in ladder if e["exceeds"]),
        "ladder": ladder,
    }
