"""esr_tpu.obs — structured host-side telemetry (docs/OBSERVABILITY.md).

One subsystem, now two halves:

**Producing** (in-process, hot-path-safe):

- :mod:`esr_tpu.obs.sink` — the structured JSONL event/metric sink
  (monotonic-clock records, counters, gauges, per-run manifest with config
  fingerprint + jax version + device kind + schema version) and the
  process-active sink registry every instrumented component checks;
- :mod:`esr_tpu.obs.trace` — ambient trace context (schema v2): spans
  carry ``trace_id``/``span_id``/``parent_id`` + monotonic begin/end,
  nested records auto-link through a ``contextvars`` context, and worker
  threads adopt their submitter's context (``capture``/``adopt``);
- :mod:`esr_tpu.obs.spans` — span-based step-time attribution: the Trainer
  decomposes each super-step's wall-clock into ``data_wait`` /
  ``stage_megabatch`` / ``dispatch`` / ``device_step`` (non-blocking) /
  ``metric_readback`` / ``checkpoint`` + residual, with derived samples/s
  and goodput — emitted as one attribution record plus a ``super_step``
  span tree;
- instrumented producers elsewhere: ``checked_jit`` compile/retrace events
  (analysis/retrace_guard.py), the ``DevicePrefetcher`` health channel
  (data/loader.py), per-chunk inference/serving spans
  (inference/engine.py, serving/server.py), and the metric writers
  (utils/writer.py, utils/trackers.py).

**Consuming, live** (obs v3 — in-process, opt-in; docs/OBSERVABILITY.md
"The live plane"):

- :mod:`esr_tpu.obs.aggregate` — :class:`LiveAggregator`: streaming
  counters/gauges + mergeable log-bucketed quantile sketches
  (:class:`QuantileSketch`, DDSketch-style) per span family, tapped into
  the active sink's record stream, with windowed snapshots in the offline
  reporter's dotted namespace;
- :mod:`esr_tpu.obs.http` — dependency-free HTTP exposition over the
  aggregator: ``/metrics`` (Prometheus v0.0.4), ``/healthz`` (component
  health registry), ``/slo`` (live multi-window burn-rate evaluation of
  ``configs/slo.yml``, 200/429/503);
- :mod:`esr_tpu.obs.device` — ``DeviceWatermark`` memory gauges
  (None-tolerant on CPU) and the bounded ``ProfilerCapture``
  (``--profile-steps``) that stamps on-chip captures into the stream.

**The fleet view** (obs v5 — docs/OBSERVABILITY.md "The fleet view"):

- :mod:`esr_tpu.obs.fleetview` — :class:`FleetAggregator` merging N
  replicas' ``/snapshot`` wire documents (versioned, sketch-exact —
  ``aggregate.snapshot_wire``/``parse_snapshot_wire``) into one fleet
  rollup in the same dotted namespace, with per-replica staleness
  tracking, a quorum ``/healthz``, merged multi-window ``/slo``, the
  ``/fleet`` topology endpoint, and the advisory ``desired_replicas``
  scaling signal (:class:`ScalingPolicy`, ``configs/fleet_scale.yml``).

**The numerics plane** (obs v4 — docs/OBSERVABILITY.md "The numerics
plane"):

- :mod:`esr_tpu.obs.numerics` — the host half of the value-telemetry
  dual: per-tag stats-vector readback (merged under the same reduce law
  as the on-device accumulation in ``esr_tpu.ops.numerics``), the
  ``numerics`` JSONL record rollup shared verbatim between the offline
  reporter and the live aggregator (``numerics.finite_frac`` gates both
  through one SLO YAML), layer-named anomaly attribution for the
  AnomalyGuard's rollback events, the ``/healthz`` numerics source, and
  the precision-drift attribution harness
  (``python -m esr_tpu.obs drift``).

**Consuming, offline** (``python -m esr_tpu.obs``):

- :mod:`esr_tpu.obs.export` — telemetry.jsonl → Chrome trace-event /
  Perfetto JSON (one track per host thread, virtual tracks per lane and
  request class, counter tracks), v1 files convert too;
- :mod:`esr_tpu.obs.report` — offline rollup (goodput, per-span p50/p99,
  per-class window-latency distributions, trace completeness) gated
  against declarative SLO thresholds (``configs/slo.yml``) with CI-ready
  exit codes.

Design rules: stdlib-only (importable from the NumPy-only data layer and
accelerator-free CI hosts; only the SLO loader touches yaml, lazily), and
host-side only — no ``obs`` call may appear inside jitted/scanned code
(enforced by analysis rules ESR007/ESR010 and the self-check in
``tests/test_obs.py``).
"""

from esr_tpu.obs import trace
from esr_tpu.obs.aggregate import (
    LiveAggregator,
    QuantileSketch,
    parse_snapshot_wire,
)
from esr_tpu.obs.fleetview import (
    FleetAggregator,
    ScalingPolicy,
    start_fleet_plane,
)
from esr_tpu.obs.sink import (
    SCHEMA_VERSION,
    TelemetrySink,
    active_sink,
    config_fingerprint,
    run_manifest,
    set_active_sink,
)
from esr_tpu.obs.spans import StepAttribution, StepSpans

__all__ = [
    "SCHEMA_VERSION",
    "FleetAggregator",
    "LiveAggregator",
    "QuantileSketch",
    "ScalingPolicy",
    "parse_snapshot_wire",
    "start_fleet_plane",
    "TelemetrySink",
    "active_sink",
    "config_fingerprint",
    "run_manifest",
    "set_active_sink",
    "StepAttribution",
    "StepSpans",
    "trace",
]
