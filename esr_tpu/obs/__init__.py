"""esr_tpu.obs — structured host-side telemetry (docs/OBSERVABILITY.md).

One subsystem, three pieces:

- :mod:`esr_tpu.obs.sink` — the structured JSONL event/metric sink
  (monotonic-clock records, counters, gauges, per-run manifest with config
  fingerprint + jax version + device kind + schema version) and the
  process-active sink registry every instrumented component checks;
- :mod:`esr_tpu.obs.spans` — span-based step-time attribution: the Trainer
  decomposes each super-step's wall-clock into ``data_wait`` /
  ``stage_megabatch`` / ``dispatch`` / ``device_step`` (non-blocking) /
  ``metric_readback`` / ``checkpoint`` + residual, with derived samples/s
  and goodput;
- instrumented producers elsewhere: ``checked_jit`` compile/retrace events
  (analysis/retrace_guard.py), the ``DevicePrefetcher`` health channel
  (data/loader.py), per-sequence inference latency spans
  (inference/harness.py), and the metric writers (utils/writer.py,
  utils/trackers.py).

Design rules: stdlib-only (importable from the NumPy-only data layer and
accelerator-free CI hosts), and host-side only — no ``obs`` call may appear
inside jitted/scanned code (enforced by analysis rule ESR007 and the
self-check in ``tests/test_obs.py``).
"""

from esr_tpu.obs.sink import (
    SCHEMA_VERSION,
    TelemetrySink,
    active_sink,
    config_fingerprint,
    run_manifest,
    set_active_sink,
)
from esr_tpu.obs.spans import StepAttribution, StepSpans

__all__ = [
    "SCHEMA_VERSION",
    "TelemetrySink",
    "active_sink",
    "config_fingerprint",
    "run_manifest",
    "set_active_sink",
    "StepAttribution",
    "StepSpans",
]
