"""Offline run rollup + SLO gate over a telemetry.jsonl.

``python -m esr_tpu.obs report run/telemetry.jsonl --slo configs/slo.yml``
turns the JSONL firehose into one machine-checkable verdict: goodput,
per-span-name p50/p99, backpressure/retrace/stall totals, per-request-
class window-latency distributions, and trace completeness — evaluated
against declarative thresholds so bench/CI can gate on regressions
instead of eyeballing JSONL (the role Perfetto-style tooling and
VirtualFlow's per-virtual-node accounting play in production stacks).

Report shape (all sections always present; serving/training sections are
empty-but-typed when the run had no such activity):

- ``goodput`` — the headline. Source "attribution" (wall-weighted mean of
  the Trainer's per-super-step goodput) when the run trained; source
  "serving"/"inference" (fused-chunk busy time over the chunk wall, from
  ``serve_chunk``/``infer_chunk`` spans respectively) when it served or
  streamed offline; ``value: None`` when none — which the shipped SLO
  config treats as a violation.
- ``spans`` — per span name: count, total seconds, p50/p99/max
  milliseconds (pure-python linear-interpolation percentiles, pinned
  against numpy in tests/test_obs_report.py).
- ``counters`` / ``events`` — final running totals and occurrence counts
  (``serve_backpressure``, ``prefetch_stall``, ``compile`` retraces, …).
- ``serving`` — requests/completed/errors, windows, per-class
  window-latency p50/p99 rebuilt from ``serve_chunk_part`` spans (each
  chunk participation contributes its resolve latency once per window —
  the same definition ``ServingEngine.report`` uses live).
- ``traces`` — per ``serve_request_done``: is the terminal event
  connected to its ``serve_request`` root through parent links? Counted
  as ``complete``/``incomplete`` (+ ids), the acceptance criterion for a
  causally-reconstructable request journey (``status: shed`` submits are
  skipped — no journey ever existed).
- ``faults`` — fault -> recovery completeness (docs/RESILIENCE.md):
  every ``fault_injected`` event matched one-to-one against
  ``recovery_*`` events (by ``fault_id``, then by ``site``); the chaos
  gate requires ``unrecovered == 0``.
- ``numerics`` — the numerics plane's rollup (obs v4): per probe tag the
  worst-case max-abs/rms/underflow/overflow readings and the headline
  ``finite_frac`` (the worst tag's finite fraction), built by the SAME
  ``obs.numerics.ingest``/``rollup`` pair the live aggregator uses, so
  ``numerics.finite_frac`` in ``configs/slo.yml`` gates a finished file
  and a live ``/slo`` window identically.

SLO YAML (``configs/slo.yml``)::

    schema: 1
    rules:
      - name: goodput-positive     # any label, shows in the verdict
        metric: goodput.value      # dotted path into the report
        min: 1.0e-6                # and/or `max:`
        allow_missing: true        # optional: absent metric != violation

Exit codes (CLI, obs/__main__.py): 0 every rule passed, 1 violation(s),
2 unreadable input/SLO file. The report module itself is stdlib-only;
only SLO loading imports yaml (lazily — a repo dependency already).
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

from esr_tpu.obs.export import _span_edges, read_telemetry

__all__ = [
    "percentile",
    "percentile_ms",
    "build_report",
    "load_slo",
    "evaluate_slo",
    "evaluate_slo_window",
    "report_file",
    "split_label",
    "merge_fleet_reports",
    "report_files",
]


def percentile(values: Sequence[float], q: float) -> Optional[float]:
    """The ``q``-th percentile (0..100) with linear interpolation between
    order statistics — numpy.percentile's default method, implemented
    stdlib-only and pinned against numpy in tests.

    THE percentile definition of the whole telemetry surface: the offline
    reporter, ``ServingEngine.report``/``summary`` (the live per-request
    numbers), and the live aggregator's sketch interpolation all route
    through this method so the three views can never drift on percentile
    convention (the ``np.percentile``-vs-pure-python split this PR
    removed)."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return None
    if len(vals) == 1:
        return vals[0]
    rank = (q / 100.0) * (len(vals) - 1)
    lo = int(math.floor(rank))
    hi = int(math.ceil(rank))
    if lo == hi:
        return vals[lo]
    frac = rank - lo
    return vals[lo] * (1.0 - frac) + vals[hi] * frac


def percentile_ms(
    values_s: Sequence[float], q: float, ndigits: int = 3
) -> Optional[float]:
    """:func:`percentile` over seconds, reported in rounded milliseconds —
    the shared seconds→ms convention of the serving summaries and the
    reporter's span tables."""
    p = percentile(values_s, q)
    return None if p is None else round(p * 1e3, ndigits)


def _pctl_ms(lat_s: Sequence[float]) -> Dict[str, Optional[float]]:
    return {
        "p50_ms": _round(percentile(lat_s, 50), 1e3),
        "p99_ms": _round(percentile(lat_s, 99), 1e3),
        "max_ms": _round(max(lat_s) if lat_s else None, 1e3),
    }


def _round(v: Optional[float], scale: float = 1.0) -> Optional[float]:
    return None if v is None else round(v * scale, 4)


# the terminal event a complete request trace must hang off of
_REQUEST_TERMINAL = "serve_request_done"

# terminal statuses that legitimately have NO journey root in the file
# that carries them: `shed` never had a journey; `replica_lost` and
# `failover_retry_exhausted` are ROUTER-emitted (the journey spans live
# in the replica files, the router classifies the outcome —
# docs/RESILIENCE.md status taxonomy). `migrated` is NOT here: the
# source replica emits it WITH its root span, so it stays walkable.
_ROOTLESS_STATUSES = frozenset(
    {"shed", "replica_lost", "failover_retry_exhausted"}
)

# attempt-terminal statuses excluded from request/window totals: the
# stream CONTINUED on another replica, whose final terminal carries the
# full-stream accounting — folding these in would double-count.
_CONTINUED_STATUSES = frozenset({"shed", "migrated", "replica_lost"})


def _trace_completeness(records: List[Dict]) -> Dict:
    """Walk every ``serve_request_done`` event's parent chain: complete
    iff it reaches a root span (``parent_id: None``) of the same trace
    through recorded spans."""
    spans = {
        r["span_id"]: r
        for r in records
        if r.get("type") == "span" and r.get("span_id")
    }
    requests = 0
    complete = 0
    incomplete_ids: List[str] = []
    for rec in records:
        if rec.get("type") != "event" or rec.get("name") != _REQUEST_TERMINAL:
            continue
        if rec.get("status") in _ROOTLESS_STATUSES:
            # classified, not incomplete: these statuses never had a
            # journey root in THIS file (module constant above)
            continue
        requests += 1
        rid = rec.get("request", "?")
        trace_id = rec.get("trace_id")
        ok = False
        if trace_id is not None:
            seen = set()
            pid = rec.get("parent_id")
            while pid is not None and pid not in seen:
                seen.add(pid)
                parent = spans.get(pid)
                if parent is None or parent.get("trace_id") != trace_id:
                    break
                if parent.get("parent_id") is None:
                    ok = True
                    break
                pid = parent.get("parent_id")
        if ok:
            complete += 1
        else:
            incomplete_ids.append(rid)
    return {
        "requests": requests,
        "complete": complete,
        "incomplete": requests - complete,
        "incomplete_ids": incomplete_ids,
    }


def _fault_completeness(records: List[Dict]) -> Dict:
    """Match every ``fault_injected`` event to a ``recovery_*`` event —
    the chaos gate's acceptance check (docs/RESILIENCE.md): a fault the
    run did not visibly recover from is a broken recovery path.

    Matching is two-pass and one-to-one: first by explicit ``fault_id``
    (recovery paths that know their cause carry it), then by ``site`` in
    record order (recovery paths that only observe the symptom — the
    stall watchdog — still pair with the fault they answered). A fault's
    symptom can surface one stage downstream of its injection point (a
    corrupted prefetch batch is caught by the TRAIN STEP's anomaly
    guard), so site matching accepts the documented answer sites."""
    answers = {
        "prefetch": ("prefetch", "train_step"),
        "train_step": ("train_step",),
        "ckpt_commit": ("ckpt_commit",),
        "ckpt_restore": ("ckpt_restore",),
        "serve_chunk": ("serve_chunk",),
        "fleet_router": ("fleet_router",),
    }
    faults = [
        r for r in records
        if r.get("type") == "event" and r.get("name") == "fault_injected"
    ]
    recoveries = [
        r for r in records
        if r.get("type") == "event"
        and str(r.get("name", "")).startswith("recovery_")
    ]
    used = [False] * len(recoveries)
    matched: Dict[int, Dict] = {}
    for fi, fault in enumerate(faults):
        fid = fault.get("fault_id")
        for ri, rec in enumerate(recoveries):
            if not used[ri] and fid and rec.get("fault_id") == fid:
                used[ri] = True
                matched[fi] = rec
                break
    for fi, fault in enumerate(faults):
        if fi in matched:
            continue
        ok_sites = answers.get(fault.get("site"), (fault.get("site"),))
        for ri, rec in enumerate(recoveries):
            if not used[ri] and rec.get("site") in ok_sites:
                used[ri] = True
                matched[fi] = rec
                break
    by_site: Dict[str, Dict] = {}
    unrecovered_ids: List[str] = []
    for fi, fault in enumerate(faults):
        site = fault.get("site", "?")
        slot = by_site.setdefault(site, {"injected": 0, "recovered": 0})
        slot["injected"] += 1
        if fi in matched:
            slot["recovered"] += 1
        else:
            unrecovered_ids.append(fault.get("fault_id", "?"))
    return {
        "injected": len(faults),
        "recovered": len(matched),
        "unrecovered": len(faults) - len(matched),
        "unrecovered_ids": unrecovered_ids,
        "recovery_events": len(recoveries),
        "by_site": {k: by_site[k] for k in sorted(by_site)},
    }


def build_report(
    records: List[Dict],
    manifest: Optional[Dict] = None,
    torn_lines: int = 0,
) -> Dict:
    """One run's telemetry records → the rollup dict (module docstring)."""
    span_secs: Dict[str, List[float]] = {}
    counters: Dict[str, float] = {}
    event_counts: Dict[str, int] = {}
    attributions: List[Dict] = []
    class_lat: Dict[str, List[float]] = {}
    class_windows: Dict[str, int] = {}
    chunk_edges: List[Tuple[float, float]] = []
    chunk_busy = 0.0
    chunk_kinds: set = set()
    chunk_windows_valid = 0
    windows_skipped = 0
    requests_done = 0
    requests_failed = 0
    windows_total = 0
    statuses: Dict[str, int] = {}
    numerics_states: Dict[str, Dict] = {}

    from esr_tpu.obs import numerics as _numerics

    for rec in records:
        kind = rec.get("type")
        name = rec.get("name", "")
        if kind == "span":
            span_secs.setdefault(name, []).append(
                float(rec.get("seconds", 0.0) or 0.0)
            )
            if name == "serve_chunk_part":
                cls = rec.get("cls", "default")
                n = int(rec.get("windows", 0) or 0)
                class_lat.setdefault(cls, []).extend(
                    [float(rec.get("seconds", 0.0))] * n
                )
                class_windows[cls] = class_windows.get(cls, 0) + n
            elif name in ("serve_chunk", "infer_chunk"):
                chunk_edges.append(_span_edges(rec))
                chunk_busy += float(rec.get("seconds", 0.0) or 0.0)
                chunk_kinds.add(name)
                # activity gating (ISSUE 12): windows the SERVING
                # scheduler served with zero lane compute. serve_chunk
                # only — folding infer_chunk windows into the computed
                # side would report active_window_frac 1.0 for
                # inference-only files and understate serving savings
                if name == "serve_chunk":
                    chunk_windows_valid += int(rec.get("windows", 0) or 0)
                    windows_skipped += int(
                        rec.get("skipped_windows", 0) or 0
                    )
        elif kind == "counter":
            counters[name] = float(rec.get("total", 0.0) or 0.0)
        elif kind == "event":
            event_counts[name] = event_counts.get(name, 0) + 1
            if name == "serve_gating_flush":
                # gated windows from after the last dispatched chunk
                # (serving/server.py): no span carries them
                windows_skipped += int(rec.get("skipped", 0) or 0)
            if name == _REQUEST_TERMINAL:
                status = rec.get("status") or (
                    "ok" if rec.get("completed", False) else "bad_stream"
                )
                statuses[status] = statuses.get(status, 0) + 1
                if status in _CONTINUED_STATUSES:
                    # classified but not SERVED here: shed never ran;
                    # migrated / replica_lost continued elsewhere and the
                    # final terminal carries the full-stream totals
                    continue
                requests_done += 1
                windows_total += int(rec.get("windows", 0) or 0)
                if not rec.get("completed", False):
                    requests_failed += 1
        elif kind == "numerics":
            _numerics.ingest(numerics_states, rec)
        elif kind == "attribution":
            attributions.append(rec)

    spans_out = {
        name: {
            "count": len(vals),
            "total_s": round(sum(vals), 6),
            **_pctl_ms(vals),
        }
        for name, vals in sorted(span_secs.items())
    }

    # -- goodput ------------------------------------------------------------
    goodput: Dict = {"value": None, "source": None}
    if attributions:
        walls = [float(a.get("wall_s", 0.0) or 0.0) for a in attributions]
        goods = [float(a.get("goodput", 0.0) or 0.0) for a in attributions]
        total_wall = sum(walls)
        if total_wall > 0:
            goodput = {
                "value": round(
                    sum(w * g for w, g in zip(walls, goods)) / total_wall, 6
                ),
                "source": "attribution",
                "records": len(attributions),
                "min": round(min(goods), 6),
                "max": round(max(goods), 6),
            }
    elif chunk_edges:
        begin = min(e[0] for e in chunk_edges)
        end = max(e[1] for e in chunk_edges)
        wall = max(end - begin, 1e-9)
        goodput = {
            # resolve-one-behind overlaps dispatches, so busy/wall can
            # nominally exceed 1 — clamp like the attribution goodput
            "value": round(min(chunk_busy / wall, 1.0), 6),
            # name the tier honestly: an offline StreamingEngine run
            # (infer_chunk spans only) is "inference", not "serving"
            "source": ("serving" if "serve_chunk" in chunk_kinds
                       else "inference"),
            "busy_s": round(chunk_busy, 6),
            "wall_s": round(wall, 6),
        }

    serving = {
        "requests": requests_done,
        "completed": requests_done - requests_failed,
        "errors": requests_failed,
        "statuses": {k: statuses[k] for k in sorted(statuses)},
        "windows": windows_total,
        # how much compute activity gating saved (docs/PERF.md): idle
        # windows served without a dispatch, and the computed fraction —
        # 1.0 (or None when no chunks) means gating removed nothing
        "windows_skipped": windows_skipped,
        "active_window_frac": (
            round(chunk_windows_valid
                  / (chunk_windows_valid + windows_skipped), 6)
            if (chunk_windows_valid + windows_skipped) else None
        ),
        "preemptions": event_counts.get("serve_preempt", 0),
        "backpressure": counters.get("serve_backpressure", 0.0),
        "classes": {
            cls: {
                "windows": class_windows.get(cls, 0),
                "window_latency_p50_ms": _round(
                    percentile(lat, 50), 1e3
                ),
                "window_latency_p99_ms": _round(
                    percentile(lat, 99), 1e3
                ),
            }
            for cls, lat in sorted(class_lat.items())
        },
    }

    return {
        "schema_version": (manifest or {}).get("schema_version"),
        "records": len(records),
        "torn_lines": torn_lines,
        "goodput": goodput,
        "spans": spans_out,
        "counters": {k: counters[k] for k in sorted(counters)},
        "events": {k: event_counts[k] for k in sorted(event_counts)},
        "serving": serving,
        "traces": _trace_completeness(records),
        "faults": _fault_completeness(records),
        "numerics": _numerics.rollup(numerics_states),
    }


# -- SLO evaluation ---------------------------------------------------------


def load_slo(path: str) -> Dict:
    """Parse an SLO YAML; raises ``ValueError`` on a malformed file (the
    CLI maps that to exit 2 — a broken gate must not silently pass)."""
    import yaml  # lazy: the only non-stdlib import in esr_tpu.obs

    with open(path) as f:
        try:
            doc = yaml.safe_load(f)
        except yaml.YAMLError as e:
            # normalize to the documented contract: a broken gate file is
            # exit 2 (unreadable), never exit 1 (a "real" SLO violation)
            raise ValueError(f"SLO file {path!r} is not valid YAML: {e}")
    if not isinstance(doc, dict) or not isinstance(doc.get("rules"), list):
        raise ValueError(
            f"SLO file {path!r} must be a mapping with a `rules:` list "
            "(docs/OBSERVABILITY.md)"
        )
    for rule in doc["rules"]:
        if not isinstance(rule, dict) or "metric" not in rule:
            raise ValueError(f"SLO rule without a `metric:`: {rule!r}")
        if "min" not in rule and "max" not in rule:
            raise ValueError(
                f"SLO rule {rule.get('name', rule['metric'])!r} has "
                "neither `min:` nor `max:`"
            )
    return doc


def _lookup(report: Dict, dotted: str):
    cur = report
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def evaluate_slo(report: Dict, slo: Dict) -> Tuple[bool, List[Dict]]:
    """Apply every rule; returns ``(all_ok, verdicts)`` where each verdict
    is ``{name, metric, value, min, max, ok, reason}``."""
    verdicts: List[Dict] = []
    all_ok = True
    for rule in slo.get("rules", []):
        metric = rule["metric"]
        value = _lookup(report, metric)
        lo = rule.get("min")
        hi = rule.get("max")
        verdict = {
            "name": rule.get("name", metric),
            "metric": metric,
            "value": value,
            "min": lo,
            "max": hi,
        }
        if value is None or (
            isinstance(value, float) and not math.isfinite(value)
        ):
            if rule.get("allow_missing", False) and value is None:
                verdict.update(ok=True, reason="missing (allowed)")
            else:
                verdict.update(
                    ok=False,
                    reason="metric missing or non-finite",
                )
        else:
            try:
                num = float(value)
            except (TypeError, ValueError):
                verdict.update(ok=False, reason="metric not numeric")
                verdicts.append(verdict)
                all_ok = False
                continue
            if lo is not None and num < float(lo):
                verdict.update(ok=False, reason=f"{num} < min {lo}")
            elif hi is not None and num > float(hi):
                verdict.update(ok=False, reason=f"{num} > max {hi}")
            else:
                verdict.update(ok=True, reason="within bounds")
        all_ok = all_ok and verdict["ok"]
        verdicts.append(verdict)
    return all_ok, verdicts


def evaluate_slo_window(snapshot: Dict, slo: Dict) -> Dict:
    """One LIVE window's burn verdict — the windowed relaxation of
    :func:`evaluate_slo`, shared by the per-replica ``/slo`` endpoint
    (obs/http.py) and the fleet plane's merged-window evaluation
    (obs/fleetview.py) so the two can never diverge on semantics.

    Absence of evidence is not a burn: an EMPTY window (zero records —
    an idle replica) is "no data" as a whole, and a rule whose metric is
    simply ABSENT from the window (goodput between attribution records,
    serving classes before the first resolve) is skipped-as-missing
    rather than violated. The offline gate keeps its strict
    missing=violation semantics for finished runs; a live WINDOW
    legitimately lacks subsystems that did not emit during it, and
    scoring that as a sustained burn would make the router contract
    (503 → drain) kill healthy replicas on every traffic lull or cadence
    gap. A present-but-non-finite metric (NaN) still violates.

    Returns ``{"ok", "no_data", "violations", "missing"}``.
    """
    if snapshot.get("records", 0) == 0:
        return {"ok": True, "no_data": True, "violations": [],
                "missing": []}
    _ok, verdicts = evaluate_slo(snapshot, slo)
    missing = [v["name"] for v in verdicts
               if not v["ok"] and v["value"] is None]
    violations = [v for v in verdicts
                  if not v["ok"] and v["value"] is not None]
    return {"ok": not violations, "no_data": False,
            "violations": violations, "missing": missing}


def report_file(
    telemetry_path: str,
    slo_path: Optional[str] = None,
    out_path: Optional[str] = None,
    run_index: int = -1,
) -> Tuple[Dict, int]:
    """The CLI body: read, roll up, optionally gate; returns
    ``(document, exit_code)``. The document always contains the report;
    with an SLO it adds ``{"slo": {"ok", "verdicts"}}``. ``run_index``
    selects a run of an appended multi-run file (obs/export.py)."""
    manifest, records, torn = read_telemetry(
        telemetry_path, run_index=run_index
    )
    report = build_report(records, manifest, torn_lines=torn)
    doc: Dict = {"report": report}
    code = 0
    if slo_path is not None:
        slo = load_slo(slo_path)
        ok, verdicts = evaluate_slo(report, slo)
        doc["slo"] = {"ok": ok, "path": slo_path, "verdicts": verdicts}
        code = 0 if ok else 1
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=2)
    return doc, code


# -- fleet rollup: one report over many telemetry files ----------------------


def split_label(arg: str) -> Tuple[str, str]:
    """``label=path`` -> ``(label, path)``; a bare path derives its label
    from the filename (``telemetry_r0.jsonl`` -> ``telemetry_r0``), or —
    for the conventional per-run ``telemetry.jsonl`` name — from the
    parent directory, so replica rows stay tellable apart by default."""
    if "=" in arg and not os.path.exists(arg):
        label, _, path = arg.partition("=")
        if label and path:
            return label, path
    base = os.path.basename(arg)
    stem = base[: -len(".jsonl")] if base.endswith(".jsonl") else base
    if stem == "telemetry":
        parent = os.path.basename(os.path.dirname(os.path.abspath(arg)))
        stem = parent or stem
    return stem, arg


def merge_fleet_reports(
    labeled: List[Tuple[str, Optional[Dict], List[Dict], int]],
) -> Dict:
    """Fleet-level rollup over per-replica telemetry (docs/SERVING.md
    "The fleet"): ``labeled`` is ``(replica label, manifest, records,
    torn)`` per file.

    The fleet sections are built from the CONCATENATED record stream, so
    everything distribution-shaped is EXACT — percentiles over durations
    are order-free (the same merge==concat property the live plane's
    ``QuantileSketch`` pins), fault->recovery matching and trace
    completeness walk ids that are unique across processes. Two sections
    need per-file composition instead: ``counters`` carry running totals
    (last-wins under concat; the fleet sums each file's final total) and
    ``goodput`` walls live on per-file clock bases (the fleet reports a
    wall-weighted mean plus the per-replica values). A ``replicas``
    section labels each file's own rollup row, so per-replica and fleet
    views come from the same files."""
    if not labeled:
        raise ValueError("merge_fleet_reports needs at least one file")
    per: List[Tuple[str, Dict]] = [
        (label, build_report(records, manifest, torn_lines=torn))
        for label, manifest, records, torn in labeled
    ]
    all_records = [rec for _, _, records, _ in labeled for rec in records]
    fleet = build_report(
        all_records, labeled[0][1],
        torn_lines=sum(torn for _, _, _, torn in labeled),
    )
    counters: Dict[str, float] = {}
    for _, rep in per:
        for name, total in rep["counters"].items():
            counters[name] = counters.get(name, 0.0) + total
    fleet["counters"] = {k: counters[k] for k in sorted(counters)}
    valued = [(label, rep["goodput"]) for label, rep in per
              if rep["goodput"]["value"] is not None]
    if valued:
        weights = [float(g.get("wall_s") or 0.0) or 1.0 for _, g in valued]
        fleet["goodput"] = {
            "value": round(
                sum(g["value"] * w for (_, g), w in zip(valued, weights))
                / sum(weights), 6,
            ),
            "source": "fleet",
            "wall_s": round(max(
                float(g.get("wall_s") or 0.0) for _, g in valued
            ), 6),
            "busy_s": round(sum(
                float(g.get("busy_s") or 0.0) for _, g in valued
            ), 6),
            "replicas": {label: g["value"] for label, g in valued},
        }
    else:
        fleet["goodput"] = {"value": None, "source": "fleet"}
    fleet["replicas"] = {
        label: {
            "records": rep["records"],
            "torn_lines": rep["torn_lines"],
            "goodput": rep["goodput"]["value"],
            "requests": rep["serving"]["requests"],
            "completed": rep["serving"]["completed"],
            "errors": rep["serving"]["errors"],
            "windows": rep["serving"]["windows"],
            "statuses": rep["serving"]["statuses"],
            "preemptions": rep["serving"]["preemptions"],
            "faults_injected": rep["faults"]["injected"],
            "faults_unrecovered": rep["faults"]["unrecovered"],
            "traces_incomplete": rep["traces"]["incomplete"],
        }
        for label, rep in per
    }
    return fleet


def report_files(
    telemetry_args: Sequence[str],
    slo_path: Optional[str] = None,
    out_path: Optional[str] = None,
    run_index: int = -1,
) -> Tuple[Dict, int]:
    """Multi-file CLI body (``python -m esr_tpu.obs report a.jsonl
    b.jsonl ...``): one file behaves exactly like :func:`report_file`;
    several are merged into the fleet rollup (labels via
    :func:`split_label` — ``r0=path`` or filename-derived) and the SLO
    gate evaluates the FLEET-level report."""
    if len(telemetry_args) == 1 and "=" not in telemetry_args[0]:
        return report_file(telemetry_args[0], slo_path, out_path,
                           run_index=run_index)
    labeled = []
    for arg in telemetry_args:
        label, path = split_label(arg)
        manifest, records, torn = read_telemetry(path, run_index=run_index)
        labeled.append((label, manifest, records, torn))
    report = merge_fleet_reports(labeled)
    doc: Dict = {"report": report}
    code = 0
    if slo_path is not None:
        slo = load_slo(slo_path)
        ok, verdicts = evaluate_slo(report, slo)
        doc["slo"] = {"ok": ok, "path": slo_path, "verdicts": verdicts}
        code = 0 if ok else 1
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=2)
    return doc, code
