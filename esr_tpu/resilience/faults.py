"""The deterministic fault-injection plane (docs/RESILIENCE.md).

A :class:`FaultPlan` is a seeded schedule of :class:`FaultSpec`s keyed by
``site x index`` — *which* failure, *where*, at *which* step/chunk/save
ordinal. Production code carries :func:`fire` hooks at the injection
sites; with no plan installed a hook is one module-global ``None`` check
(measured < 100 ns — the zero-cost-when-disabled contract, pinned by
``tests/test_resilience.py``), and the hooks never enter jitted code, so
the jaxpr audit and the compiled programs are byte-identical with or
without the subsystem (``program_audit`` stays CLEAN).

Sites and kinds (the catalog; docs/RESILIENCE.md has the full table):

====================  =====================================================
site                  kinds
====================  =====================================================
``prefetch``          ``corrupt`` (NaN-poison a host megabatch before
                      staging), ``stall`` (sleep the producer ``arg``
                      seconds — exercises the stall watchdog)
``train_step``        ``nan_loss`` (force the super-step's readback loss
                      to NaN — exercises the anomaly guard),
                      ``dispatch_error`` (simulated transient
                      ``XlaRuntimeError`` at dispatch — exercises the
                      bounded dispatch retry)
``ckpt_commit``       ``fail`` (commit attempt raises — exercises the
                      backoff retry), ``torn`` (raise between the Orbax
                      array write and the ``meta.yml`` marker — a torn
                      directory the next attempt overwrites)
``ckpt_restore``      ``truncate`` (truncate the largest array file of the
                      checkpoint about to be restored — exercises the
                      integrity fallback to the prior commit)
``serve_chunk``       ``lane_fault`` (a bound lane's pull raises),
                      ``stream_error`` (the stream iterator raises
                      mid-iteration), ``preempt_signal`` (simulated host
                      preemption — every bound lane is drained/saved and
                      requeued)
``fleet_router``      ``replica_kill`` (a replica dies abruptly — its
                      streams fail over elsewhere), ``replica_partition``
                      (a replica becomes unreachable — fenced, then failed
                      over), ``router_handoff`` (forced voluntary drain —
                      every stream migrates bit-exactly over the
                      lane-state wire format). ``arg`` selects the target
                      replica index; keyed by the router's round ordinal.
====================  =====================================================

Everything here is stdlib+numpy only: the data layer imports this module
(analysis rule ESR004 — no jax below the loader), and the plan must be
installable in processes that never touch an accelerator.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

SITES = ("prefetch", "train_step", "ckpt_commit", "ckpt_restore",
         "serve_chunk", "fleet_router")

_KINDS: Dict[str, Tuple[str, ...]] = {
    "prefetch": ("corrupt", "stall"),
    "train_step": ("nan_loss", "dispatch_error"),
    "ckpt_commit": ("fail", "torn"),
    "ckpt_restore": ("truncate",),
    "serve_chunk": ("lane_fault", "stream_error", "preempt_signal"),
    "fleet_router": ("replica_kill", "replica_partition",
                     "router_handoff"),
}


class InjectedFault(RuntimeError):
    """An error raised *by* the fault plane at an injection site.

    ``transient=True`` marks faults the matching recovery path is allowed
    to retry (a simulated dispatch ``XlaRuntimeError``, a failing commit
    attempt); the recovery machinery treats it exactly like the real error
    class it stands in for."""

    def __init__(self, spec: "FaultSpec", transient: bool = True):
        super().__init__(
            f"injected fault {spec.fault_id} "
            f"(site={spec.site}, kind={spec.kind}, index={spec.index})"
        )
        self.spec = spec
        self.transient = transient


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault: fire ``kind`` at ``site`` when that site's
    ordinal counter reaches ``index``. ``arg`` is the kind-specific knob
    (stall seconds, target lane); ``fault_id`` is stamped at plan build
    time and rides every telemetry record the fault causes."""

    site: str
    index: int
    kind: str
    arg: float = 0.0
    fault_id: str = ""

    def __post_init__(self):
        if self.site not in _KINDS:
            raise ValueError(f"unknown fault site {self.site!r}; "
                             f"sites: {sorted(_KINDS)}")
        if self.kind not in _KINDS[self.site]:
            raise ValueError(
                f"unknown kind {self.kind!r} for site {self.site!r}; "
                f"kinds: {_KINDS[self.site]}"
            )


class FaultPlan:
    """A deterministic schedule of faults, consumed one ``(site, index)``
    lookup at a time.

    The plan is *explicit* (a list of specs) or *seeded*
    (:meth:`seeded` derives a reproducible schedule from an integer seed).
    Each spec fires at most once — :func:`fire` pops it — and every firing
    is appended to :attr:`injected` (the host-side ledger the chaos bench
    cross-checks against the telemetry stream). Thread-safe: the
    prefetcher producer, the checkpoint writer, and the main loop all
    consult the same installed plan.
    """

    def __init__(self, specs: Sequence[FaultSpec] = ()):
        self._lock = threading.Lock()
        self._pending: Dict[Tuple[str, int], List[FaultSpec]] = {}
        self.injected: List[FaultSpec] = []
        self._n = 0
        for spec in specs:
            self.add(spec)

    # -- construction --------------------------------------------------------

    def add(self, spec: FaultSpec) -> FaultSpec:
        if not spec.fault_id:
            spec = FaultSpec(
                spec.site, spec.index, spec.kind, spec.arg,
                fault_id=f"{spec.site}:{spec.index}:{spec.kind}:{self._n}",
            )
        self._n += 1
        self._pending.setdefault((spec.site, spec.index), []).append(spec)
        return spec

    @classmethod
    def seeded(
        cls,
        seed: int,
        n_faults: int = 5,
        sites: Sequence[str] = SITES,
        max_index: int = 8,
        stall_s: float = 0.25,
    ) -> "FaultPlan":
        """A reproducible random schedule: ``n_faults`` faults dealt
        round-robin over ``sites`` (so a small plan still covers many
        distinct sites), kinds and indices drawn from a seeded generator.
        Same seed -> identical plan, process- and platform-independent."""
        import numpy as np

        rng = np.random.default_rng(seed)
        plan = cls()
        for i in range(int(n_faults)):
            site = sites[i % len(sites)]
            kind = _KINDS[site][int(rng.integers(len(_KINDS[site])))]
            index = int(rng.integers(max_index))
            arg = stall_s if kind == "stall" else 0.0
            plan.add(FaultSpec(site, index, kind, arg))
        return plan

    # -- consumption ---------------------------------------------------------

    def pop(self, site: str, index: int) -> List[FaultSpec]:
        """The specs scheduled at ``(site, index)``, consumed (each spec
        fires exactly once)."""
        with self._lock:
            specs = self._pending.pop((site, int(index)), [])
            self.injected.extend(specs)
            return specs

    def pending_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._pending.values())

    def summary(self) -> Dict:
        with self._lock:
            return {
                "injected": len(self.injected),
                "pending": sum(len(v) for v in self._pending.values()),
                "by_site": _count_by(self.injected, "site"),
                "by_kind": _count_by(self.injected, "kind"),
            }


def _count_by(specs: Sequence[FaultSpec], attr: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for s in specs:
        k = getattr(s, attr)
        out[k] = out.get(k, 0) + 1
    return out


# ---------------------------------------------------------------------------
# process-global plan registry — the exact pattern of obs.set_active_sink:
# None (the default) makes every hook a single attribute check, and
# installation is strictly explicit (chaos bench, chaos smoke, tests).

_PLAN: Optional[FaultPlan] = None


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install ``plan`` process-wide; returns the previous plan (restore
    it to scope installation, e.g. in tests)."""
    global _PLAN
    prev = _PLAN
    _PLAN = plan
    return prev


def clear_plan() -> None:
    install_plan(None)


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


@contextlib.contextmanager
def installed(plan: FaultPlan):
    """Scope a plan installation (the chaos harness / test idiom)."""
    prev = install_plan(plan)
    try:
        yield plan
    finally:
        install_plan(prev)


def fire(site: str, index: int, **ctx) -> Tuple[FaultSpec, ...]:
    """THE hook production call sites embed: the faults scheduled at
    ``(site, index)``, consumed, each announced as a ``fault_injected``
    telemetry event (site, kind, index, fault_id + caller context).

    With no installed plan this is one global ``None`` check returning a
    shared empty tuple — the zero-cost-when-disabled contract. The caller
    owns *enacting* each returned spec (corrupting its batch, raising,
    sleeping): the plane schedules and records, the site executes.
    """
    if _PLAN is None:
        return ()
    specs = _PLAN.pop(site, index)
    if not specs:
        return ()
    from esr_tpu.obs import active_sink

    sink = active_sink()
    if sink is not None:
        for spec in specs:
            sink.event(
                "fault_injected", site=spec.site, kind=spec.kind,
                index=spec.index, fault_id=spec.fault_id, **ctx,
            )
    return tuple(specs)


# -- kind helpers (site-side actions kept next to their schedule) -----------


def corrupt_batch(batch, fraction: float = 0.25):
    """NaN-poison a host batch dict in place (numpy only): the leading
    ``fraction`` of every float array is set to NaN — the torn-DMA /
    bad-shard stand-in. Returns the same dict for call-site chaining."""
    import numpy as np

    for key, arr in batch.items():
        arr = np.asarray(arr)
        if not np.issubdtype(arr.dtype, np.floating) or arr.size == 0:
            continue
        # .flat writes through for ANY layout — reshape(-1) on a
        # non-contiguous array returns a copy and the poison would
        # silently miss the batch
        arr.flat[: max(1, int(arr.size * fraction))] = np.nan
        batch[key] = arr
    return batch


def truncate_checkpoint_arrays(path: str) -> Optional[str]:
    """Truncate the largest file under ``<path>/state`` to half its size —
    a real on-disk corruption (the ``ckpt_restore``/``truncate`` kind), so
    the restore-integrity machinery is tested against genuine torn bytes,
    not a mock. Returns the truncated file's path (None when nothing to
    truncate)."""
    import os

    state = os.path.join(path, "state")
    largest, size = None, -1
    for dirpath, _, filenames in os.walk(state):
        for name in filenames:
            p = os.path.join(dirpath, name)
            try:
                s = os.path.getsize(p)
            except OSError:
                continue
            if s > size:
                largest, size = p, s
    if largest is None or size <= 0:
        return None
    with open(largest, "r+b") as f:
        f.truncate(max(size // 2, 1))
    return largest
