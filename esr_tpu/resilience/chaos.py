"""The scripted chaos scenario: train -> restore -> serve under faults.

One seeded, deterministic end-to-end recovery proof (docs/RESILIENCE.md),
shared by the tier-1 chaos smoke (``tests/test_chaos_smoke.py``,
``scripts/chaos_smoke.sh``) and the bench ``chaos_recovery`` stage:

1. **twin train** — a fault-free run on a synthetic corpus (the ground
   truth trajectory).
2. **chaos train** — the SAME config and seed under a
   :class:`~esr_tpu.resilience.faults.FaultPlan` covering the prefetch
   (stall + corrupt megabatch), train-step (nan loss + dispatch error),
   and checkpoint-commit (failing attempt) sites. The run must complete,
   and after rollback/skip accounting its trajectory must REJOIN the
   twin: the final checkpoint params match within ``1e-5`` rel (they are
   equal by construction — rollback replays the identical batches) and
   the per-step loss series agrees on every step both runs recorded.
3. **restore** — a validated fallback restore with the latest commit's
   arrays truncated on disk (``ckpt_restore``/``truncate``): the prior
   commit must load, loudly.
4. **serve** — a short serving session over the corpus with a lane fault
   (quarantine + bounded request retry) and a simulated preemption
   signal (drain + bit-identical resume); every request must terminate
   with a classified status.

Telemetry: phase 2 writes the chaos run's ``telemetry.jsonl`` (the
Trainer owns its sink); phases 3–4 share ``serve_telemetry.jsonl``.
``python -m esr_tpu.obs report`` over each must show
``faults.unrecovered == 0`` — the standing chaos gate
(``configs/slo_chaos.yml``).

CLI: ``python -m esr_tpu.resilience.chaos --out DIR [--seed N]`` prints
the summary JSON and exits 0 iff every acceptance property held.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

from esr_tpu.resilience.faults import FaultPlan, FaultSpec, installed

# scenario scale (kept tiny: the whole thing must run in a CPU smoke)
ITERATIONS = 10
SAVE_PERIOD = 4
BATCH_SIZE = 8
CORRUPT_ITER = 5          # after the first committed save (SAVE_PERIOD)
STALL_ITEM = 1
COMMIT_FAIL_ITER = 2 * SAVE_PERIOD
STALL_S = 2.5
STALL_TIMEOUT_S = 1.0


def build_corpus(root: str, n_rec: int = 4, num_frames: int = 12,
                 resolution: Tuple[int, int] = (64, 64)) -> str:
    """Synthetic HDF5 recordings + datalist, sized so one epoch covers
    the whole scenario (fault indices then map 1:1 onto iterations)."""
    from esr_tpu.data.synthetic import write_synthetic_h5

    os.makedirs(root, exist_ok=True)
    paths = []
    for i in range(n_rec):
        p = os.path.join(root, f"rec{i}.h5")
        if not os.path.exists(p):
            write_synthetic_h5(p, resolution, base_events=2048,
                               num_frames=num_frames, seed=i)
        paths.append(p)
    datalist = os.path.join(root, "datalist.txt")
    with open(datalist, "w") as f:
        f.write("\n".join(paths) + "\n")
    return datalist


def dataset_config() -> Dict:
    return {
        "scale": 2,
        "ori_scale": "down4",
        "time_bins": 1,
        "mode": "events",
        "window": 128,
        "sliding_window": 64,
        "need_gt_events": True,
        "need_gt_frame": False,
        "data_augment": {"enabled": False, "augment": [],
                         "augment_prob": []},
        "sequence": {
            "sequence_length": 4,
            "seqn": 3,
            "step_size": 2,
            "pause": {"enabled": False},
        },
    }


def train_config(out_root: str, datalist: str, basech: int = 4) -> Dict:
    loader = {
        "path_to_datalist_txt": datalist,
        "batch_size": BATCH_SIZE,
        "shuffle": True,
        "drop_last": True,
        "prefetch": 0,
        "dataset": dataset_config(),
    }
    return {
        "experiment": "chaos",
        "model": {
            "name": "DeepRecurrNet",
            "args": {"inch": 2, "basech": basech, "num_frame": 3},
        },
        "optimizer": {
            "name": "Adam",
            "args": {"lr": 1e-3, "weight_decay": 1e-4, "amsgrad": True},
        },
        "lr_scheduler": {"name": "ExponentialLR", "args": {"gamma": 0.95}},
        "trainer": {
            "output_path": out_root,
            "iteration_based_train": {
                "enabled": True,
                "iterations": ITERATIONS,
                "save_period": SAVE_PERIOD,
                "train_log_step": 4,
                "valid_step": 10**9,
                "lr_change_rate": 4000,
            },
            "monitor": "off",
            "tensorboard": False,
            "vis": {"enabled": False},
            "async_checkpoint": True,
            "k_steps": 1,
            # the resilience knobs under test (docs/RESILIENCE.md)
            "max_bad_steps": 1,
            "max_rollbacks": 2,
            "dispatch_retries": 1,
            "commit_retries": 2,
            "commit_backoff_s": 0.05,
            "prefetch_stall_timeout_s": STALL_TIMEOUT_S,
            # the numerics plane rides the chaos gate (obs v4): probes
            # are pure observers (twin parity is unchanged — pinned by
            # the params/loss checks below), and the corrupt-megabatch
            # fault's rollback must carry a layer-named bad_tag
            "numerics": True,
        },
        "train_dataloader": loader,
        "valid_dataloader": None,
    }


def build_train_plan(seed: int) -> FaultPlan:
    """The train-phase schedule: 5 faults over 3 sites. Placement is
    structural (a corrupt batch must land after the first committed save
    so rollback has a target; the commit fault must hit a save
    iteration); the seed picks among the valid slots so the gate does not
    ossify around one fixed trace."""
    import numpy as np

    rng = np.random.default_rng(seed)
    nan_iter = int(rng.integers(2, SAVE_PERIOD))          # pre-first-save
    dispatch_iter = int(rng.integers(0, SAVE_PERIOD - 1))
    if dispatch_iter == nan_iter:
        dispatch_iter = nan_iter - 1
    return FaultPlan([
        FaultSpec("prefetch", STALL_ITEM, "stall", arg=STALL_S),
        FaultSpec("prefetch", CORRUPT_ITER, "corrupt"),
        FaultSpec("train_step", nan_iter, "nan_loss"),
        FaultSpec("train_step", dispatch_iter, "dispatch_error"),
        FaultSpec("ckpt_commit", COMMIT_FAIL_ITER, "fail"),
    ])


def build_serve_plan(seed: int) -> FaultPlan:
    import numpy as np

    rng = np.random.default_rng(seed + 1)
    preempt_chunk = int(rng.integers(3, 5))
    return FaultPlan([
        FaultSpec("ckpt_restore", 0, "truncate"),
        FaultSpec("serve_chunk", 1, "lane_fault"),
        FaultSpec("serve_chunk", preempt_chunk, "preempt_signal"),
    ])


def _run_train(config: Dict, runid: str, seed: int,
               plan: Optional[FaultPlan]) -> Dict:
    import copy

    from esr_tpu.config.parser import RunConfig
    from esr_tpu.training.trainer import Trainer

    run = RunConfig(copy.deepcopy(config), runid=runid, seed=seed)
    trainer = Trainer(run)
    if len(trainer.train_loader) < ITERATIONS:
        raise RuntimeError(
            f"corpus too small: {len(trainer.train_loader)} batches/epoch "
            f"< {ITERATIONS} iterations (fault indices assume one epoch)"
        )
    t0 = time.monotonic()
    if plan is not None:
        with installed(plan):
            result = trainer.train()
    else:
        result = trainer.train()
    wall = time.monotonic() - t0
    return {
        "result": {k: round(v, 6) for k, v in result.items()},
        "wall_s": round(wall, 3),
        "save_dir": run.save_dir,
        "telemetry": os.path.join(run.log_dir, "telemetry.jsonl"),
        "rollbacks": trainer._guard.rollbacks if trainer._guard else 0,
        "skipped_iterations": (
            sorted(set(trainer._guard.skipped_iterations))
            if trainer._guard else []
        ),
        # layer-named anomaly attribution (obs v4): the most recent bad
        # super-step's first offending probe tag
        "last_bad_tag": (
            trainer._guard.last_bad_tag if trainer._guard else None
        ),
    }


def _loss_series(telemetry_path: str) -> Dict[int, float]:
    """Last-recorded ``train_loss`` per step — replayed steps overwrite
    their pre-rollback record, exactly the accounting the parity check
    needs."""
    out: Dict[int, float] = {}
    with open(telemetry_path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if (rec.get("type") == "metric"
                    and str(rec.get("name", "")).startswith("train_loss")
                    and rec.get("step") is not None):
                out[int(rec["step"])] = float(rec["value"])
    return out


def _params_max_rel_diff(path_a: str, path_b: str) -> float:
    import jax
    import numpy as np

    from esr_tpu.training.checkpoint import load_for_inference

    _, pa, _ = load_for_inference(path_a)
    _, pb, _ = load_for_inference(path_b)
    worst = 0.0
    for a, b in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        denom = np.maximum(np.abs(a), 1e-12)
        worst = max(worst, float(np.max(np.abs(a - b) / denom)))
    return worst


def _run_serve(ckpt_path: str, recordings: List[str], seed: int,
               plan: FaultPlan) -> Dict:
    from esr_tpu.serving.server import ServingEngine
    from esr_tpu.training.checkpoint import load_for_inference

    model, params, _ = load_for_inference(ckpt_path)
    cfg = dataset_config()
    cfg["sequence"] = dict(cfg["sequence"], step_size=None)
    srv = ServingEngine(
        model, params, cfg, lanes=2, preempt_quantum=0,
        lane_quarantine_k=1, request_retries=1,
    )
    rids = [srv.submit(p) for p in recordings]
    with installed(plan):
        summary = srv.run(max_wall_s=120.0)
    reports = {rid: srv.report(rid) for rid in rids}
    return {"summary": summary, "reports": reports}


def run_scenario(out_dir: str, seed: int = 0, fast: bool = False) -> Dict:
    """The whole scripted scenario; returns the machine-checkable summary
    (every acceptance property precomputed as a boolean).

    ``fast=True`` is the tier-1 profile (docs/TESTING.md): the SAME
    corpus, iteration count, fault plans, and checks, on a half-width
    model (``basech=2``) — fault placement is iteration-indexed and the
    parity checks are twin-relative, so nothing observable changes except
    wall-clock. The full profile (``basech=4``, the production smoke
    shape) stays gated in ``scripts/chaos_smoke.sh`` via the CLI."""
    from esr_tpu.obs import TelemetrySink, set_active_sink
    from esr_tpu.obs.report import report_file
    from esr_tpu.resilience.recovery import restore_with_fallback

    os.makedirs(out_dir, exist_ok=True)
    datalist = build_corpus(os.path.join(out_dir, "corpus"))
    config = train_config(out_dir, datalist, basech=2 if fast else 4)

    twin = _run_train(config, "twin", seed, None)
    train_plan = build_train_plan(seed)
    chaos = _run_train(config, "chaos", seed, train_plan)

    params_diff = _params_max_rel_diff(
        os.path.join(twin["save_dir"], f"checkpoint-iteration{ITERATIONS - 1}"),
        os.path.join(chaos["save_dir"],
                     f"checkpoint-iteration{ITERATIONS - 1}"),
    )
    twin_losses = _loss_series(twin["telemetry"])
    chaos_losses = _loss_series(chaos["telemetry"])
    common = sorted(set(twin_losses) & set(chaos_losses))
    loss_diff = max(
        (abs(twin_losses[s] - chaos_losses[s])
         / max(abs(twin_losses[s]), 1e-12) for s in common),
        default=0.0,
    )

    # phases 3-4 under one dedicated sink (restore fallback + serving)
    serve_plan = build_serve_plan(seed)
    serve_tel = os.path.join(out_dir, "serve_telemetry.jsonl")
    sink = TelemetrySink(serve_tel)
    prev = set_active_sink(sink)
    try:
        with installed(serve_plan):
            from esr_tpu.config.build import build_model, build_optimizer
            from esr_tpu.training.train_step import TrainState

            # template with the trained state's structure, for the
            # validated restore (shapes only; values are overwritten)
            import jax
            import numpy as np

            model = build_model(config["model"])
            optimizer, _ = build_optimizer(
                config["optimizer"], config.get("lr_scheduler"), None
            )
            x = np.zeros((1, 3, 16, 16, 2), np.float32)
            params = model.init(
                jax.random.PRNGKey(0), x, model.init_states(1, 16, 16)
            )
            template = TrainState.create(params, optimizer)
            state, start_iter, _, used_path = restore_with_fallback(
                chaos["save_dir"], template, config
            )
            restore = {
                "path_used": used_path,
                "start_iteration": start_iter,
                "fell_back": used_path is not None and not used_path.endswith(
                    f"checkpoint-iteration{ITERATIONS - 1}"
                ),
            }
            serve = _run_serve(
                used_path,
                [p for p in open(datalist).read().split() if p][:3],
                seed, serve_plan,
            )
    finally:
        set_active_sink(prev)
        sink.close()

    train_report, _ = report_file(chaos["telemetry"])
    serve_report, _ = report_file(serve_tel)
    tf = train_report["report"]["faults"]
    sf = serve_report["report"]["faults"]
    statuses = {r["status"] for r in serve["reports"].values()}
    sites = set(tf["by_site"]) | set(sf["by_site"])

    summary = {
        "seed": seed,
        "twin": twin,
        "chaos": chaos,
        "restore": restore,
        "serve": serve,
        "serve_telemetry": serve_tel,
        "params_max_rel_diff": params_diff,
        "loss_series_max_rel_diff": loss_diff,
        "loss_steps_compared": len(common),
        "faults": {
            "injected": tf["injected"] + sf["injected"],
            "recovered": tf["recovered"] + sf["recovered"],
            "unrecovered": tf["unrecovered"] + sf["unrecovered"],
            "sites": sorted(sites),
            "train": tf,
            "serve": sf,
        },
        "checks": {
            "params_match": params_diff <= 1e-5,
            # the skipped (nan_loss) super-step is legitimately absent
            # from the chaos series; everything else must be present AND
            # agree — a vacuous 0-step comparison must fail the gate
            "loss_series_match": (
                loss_diff <= 1e-5 and len(common) >= ITERATIONS - 2
            ),
            "all_faults_recovered": (
                tf["unrecovered"] == 0 and sf["unrecovered"] == 0
            ),
            "enough_faults": tf["injected"] + sf["injected"] >= 5,
            "enough_sites": len(sites) >= 4,
            "restore_fell_back": bool(restore["fell_back"]),
            # the rollback must be layer-named (obs v4): a corrupted
            # megabatch poisons the model input, so the guard's numerics
            # readback names a real model seam (not just "nan_loss")
            "rollback_carries_tag": (
                chaos["rollbacks"] == 0
                or chaos["last_bad_tag"] is not None
            ),
            "statuses_classified": (
                len(statuses) > 0 and None not in statuses
            ),
            "all_requests_terminal": all(
                r["status"] is not None
                for r in serve["reports"].values()
            ),
        },
    }
    summary["ok"] = all(summary["checks"].values())
    summary["recovery_overhead_frac"] = round(
        chaos["wall_s"] / max(twin["wall_s"], 1e-9) - 1.0, 4
    )
    return summary


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="scripted chaos scenario (docs/RESILIENCE.md)"
    )
    p.add_argument("--out", required=True, help="output directory")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    summary = run_scenario(args.out, seed=args.seed)
    with open(os.path.join(args.out, "CHAOS_SUMMARY.json"), "w") as f:
        json.dump(summary, f, indent=2, default=str)
    print(json.dumps(
        {"ok": summary["ok"], "checks": summary["checks"],
         "faults": {k: summary["faults"][k]
                    for k in ("injected", "recovered", "unrecovered",
                              "sites")},
         "params_max_rel_diff": summary["params_max_rel_diff"],
         "recovery_overhead_frac": summary["recovery_overhead_frac"]},
    ))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
