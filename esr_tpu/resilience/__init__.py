"""Deterministic fault injection + self-healing recovery (docs/RESILIENCE.md).

Two halves, mirroring the attack/defense split:

- :mod:`esr_tpu.resilience.faults` — a seeded, deterministic fault plane.
  A :class:`FaultPlan` schedules faults keyed by ``site x index``; call
  sites in the data loader, trainer, checkpoint commit/restore, and the
  serving chunk loop carry zero-overhead hooks (one ``None`` check when no
  plan is installed, no jitted-program changes ever — the hooks are
  host-side only).
- :mod:`esr_tpu.resilience.recovery` — the machinery that survives them:
  trainer anomaly guard + rollback, checkpoint commit retry and
  restore-time integrity validation with fallback, prefetcher stall
  watchdog, serving lane quarantine + bounded request retry.

Every injected fault emits a ``fault_injected`` event and every recovery
action a ``recovery_*`` event through the process-active telemetry sink
(``esr_tpu.obs``), so ``python -m esr_tpu.obs report`` can assert
fault -> recovery completeness offline (the ``faults`` report section).
"""

from esr_tpu.resilience.faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    active_plan,
    clear_plan,
    fire,
    install_plan,
    installed,
)
from esr_tpu.resilience.recovery import (
    AnomalyGuard,
    LaneHealth,
    RollbackSignal,
    classify_error,
    emit_recovery,
)

__all__ = [
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "active_plan",
    "clear_plan",
    "fire",
    "install_plan",
    "installed",
    "AnomalyGuard",
    "LaneHealth",
    "RollbackSignal",
    "classify_error",
    "emit_recovery",
]
