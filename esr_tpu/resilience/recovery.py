"""Self-healing recovery machinery (docs/RESILIENCE.md).

The defense half of ``esr_tpu.resilience``: every component here answers
one fault site of :mod:`esr_tpu.resilience.faults` and emits a paired
``recovery_*`` telemetry event (same ``site`` field, ``fault_id`` when the
causing fault is known) so ``python -m esr_tpu.obs report`` can prove
fault -> recovery completeness offline:

- :class:`AnomalyGuard` — per-super-step finite-loss check at the
  trainer's existing cadence-gated readback: a non-finite loss is skipped
  and logged (``recovery_skip_step``) up to ``trainer.max_bad_steps``
  consecutive bad super-steps, then :class:`RollbackSignal` sends the
  trainer back to the last *valid* committed checkpoint
  (``recovery_rollback``) with a deterministic data fast-forward.
- :func:`retry_with_backoff` — bounded exponential-backoff retry shared
  by the checkpoint commit (``recovery_ckpt_retry``) and the train-step
  dispatch (``recovery_dispatch_retry``).
- checkpoint integrity: :func:`state_digest` (sha256 over the host state
  pytree) is written as a ``digest.json`` sidecar at save;
  :func:`validate_restored` recomputes it at restore (+ a finiteness
  sweep — a committed-but-poisoned checkpoint must never be a rollback
  target); :func:`restore_with_fallback` walks committed checkpoints
  newest-first and falls back LOUDLY (``recovery_restore_fallback``) past
  corrupted ones.
- :class:`LaneHealth` — the serving circuit breaker's ledger: per-lane
  fault counts feeding the quarantine decision
  (``serving.lane_quarantine_k``) in ``serving/server.py``.

Module-level imports are stdlib+numpy only (the data layer's
``DevicePrefetcher`` imports :func:`emit_recovery`); jax/checkpoint
machinery is imported lazily inside the functions that need it.
"""

from __future__ import annotations

import hashlib
import logging
import time
from typing import Callable, Dict, List, Optional, Tuple

from esr_tpu.resilience.faults import InjectedFault

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# telemetry + classification


def emit_recovery(name: str, site: str, fault_id: Optional[str] = None,
                  **fields) -> None:
    """Emit one ``recovery_*`` event through the process-active sink
    (no-op without one) — the telemetry half of every recovery action.
    ``site`` must name the fault site being answered; the offline
    completeness check matches on it."""
    if not name.startswith("recovery_"):
        raise ValueError(f"recovery event name must start with "
                         f"'recovery_', got {name!r}")
    from esr_tpu.obs import active_sink

    sink = active_sink()
    if sink is not None:
        sink.event(name, site=site, fault_id=fault_id, **fields)


def classify_error(e: BaseException) -> str:
    """Map an exception to a small, stable error taxonomy — the
    ``error_kind`` field of per-request serving reports and
    ``serve_request_done`` events (docs/SERVING.md):

    ``injected`` (the fault plane), ``io`` (filesystem/stream I/O),
    ``bad_input`` (malformed request/recording), ``runtime`` (accelerator
    runtime error), ``internal`` (everything else)."""
    if isinstance(e, InjectedFault):
        return "injected"
    if isinstance(e, (FileNotFoundError, PermissionError, OSError, EOFError)):
        return "io"
    if isinstance(e, (ValueError, KeyError)):
        return "bad_input"
    text = f"{type(e).__name__}: {e}"
    if "XlaRuntimeError" in text or "RESOURCE_EXHAUSTED" in text or (
            "UNAVAILABLE" in text):
        return "runtime"
    return "internal"


def fault_id_of(e: BaseException) -> Optional[str]:
    """The causing fault's id when ``e`` came from the fault plane."""
    spec = getattr(e, "spec", None)
    return getattr(spec, "fault_id", None)


# ---------------------------------------------------------------------------
# bounded retry (checkpoint commit, train-step dispatch)


def retry_with_backoff(
    fn: Callable,
    retries: int,
    backoff_s: float,
    site: str,
    event: str,
    sleep=time.sleep,
    **fields,
):
    """Run ``fn()`` with up to ``retries`` retries under exponential
    backoff (``backoff_s * 2**attempt``). Every retried failure emits
    ``event`` (a ``recovery_*`` name) with the attempt ordinal and the
    classified error; the final failure re-raises untouched — bounded
    recovery never silently converts a persistent fault into a hang or a
    swallow."""
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - re-raised when exhausted
            attempt += 1
            if attempt > retries:
                raise
            emit_recovery(
                event, site=site, fault_id=fault_id_of(e),
                attempt=attempt, retries=retries,
                error_kind=classify_error(e), error=repr(e), **fields,
            )
            logger.warning(
                "%s: attempt %d/%d failed (%r); retrying in %.3fs",
                site, attempt, retries, e, backoff_s * (2 ** (attempt - 1)),
            )
            sleep(backoff_s * (2 ** (attempt - 1)))


# ---------------------------------------------------------------------------
# trainer anomaly guard


class RollbackSignal(Exception):
    """Raised by :class:`AnomalyGuard` when the bad-step budget is
    exhausted; the trainer's loop catches it, restores the last valid
    committed checkpoint, and fast-forwards the data stream.

    ``bad_tag`` carries the numerics plane's layer attribution when
    probes were enabled (obs v4): the FIRST model seam whose activations
    went non-finite (``esr_tpu.obs.numerics.first_offending_tag``), so
    the ``recovery_rollback`` event names where the poison entered
    instead of just "loss went non-finite"."""

    def __init__(self, at_iteration: int, bad_steps: int,
                 fault_id: Optional[str] = None,
                 bad_tag: Optional[str] = None):
        where = f" (first offending tag: {bad_tag})" if bad_tag else ""
        super().__init__(
            f"{bad_steps} consecutive non-finite super-steps "
            f"(last at iteration {at_iteration}){where}; rolling back"
        )
        self.at_iteration = int(at_iteration)
        self.bad_steps = int(bad_steps)
        self.fault_id = fault_id
        self.bad_tag = bad_tag


class AnomalyGuard:
    """Per-super-step finite-loss sentry for the training loop.

    :meth:`check` is called at the trainer's EXISTING cadence-gated metric
    readback (no new host syncs) with the super-step's host loss scalars.
    Finite losses reset the consecutive-bad counter. A non-finite loss:

    - emits ``recovery_skip_step`` and returns False (the caller must
      exclude the super-step from metric trackers/writer — *skip-and-log*);
    - after ``max_bad_steps`` consecutive bad super-steps, raises
      :class:`RollbackSignal` instead (the caller rolls back to the last
      valid committed checkpoint and replays — *self-heal*).

    ``max_bad_steps=0`` rolls back on the first bad super-step.

    With the numerics plane enabled (``trainer.numerics``,
    docs/OBSERVABILITY.md) the trainer passes the super-step's merged
    per-tag probe readback into :meth:`check`; a bad step then carries
    the FIRST offending model seam (``bad_tag``) on its
    ``recovery_skip_step`` / ``recovery_rollback`` events and in
    :attr:`last_bad_tag` — layer-named rollback instead of "nan_loss".
    """

    def __init__(self, max_bad_steps: int = 2):
        if max_bad_steps < 0:
            raise ValueError(
                f"max_bad_steps must be >= 0, got {max_bad_steps}"
            )
        self.max_bad_steps = int(max_bad_steps)
        self.consecutive_bad = 0
        self.skipped_iterations: List[int] = []
        self.rollbacks = 0
        # the most recent bad super-step's layer attribution (None when
        # probes are off or every tag was clean)
        self.last_bad_tag: Optional[str] = None

    def check(
        self,
        losses: List[float],
        first_iteration: int,
        fault_id: Optional[str] = None,
        numerics: Optional[Dict] = None,
    ) -> bool:
        """True when every loss is finite (metrics may be recorded).
        ``numerics``: the super-step's merged ``{tag: stats vector}``
        probe readback (host numpy; already part of the cadence-gated
        readback — no new sync)."""
        import math

        if all(math.isfinite(v) for v in losses):
            self.consecutive_bad = 0
            return True
        from esr_tpu.obs.numerics import first_offending_tag

        bad_tag = first_offending_tag(numerics)
        self.last_bad_tag = bad_tag
        self.consecutive_bad += 1
        covered = list(range(first_iteration, first_iteration + len(losses)))
        self.skipped_iterations.extend(covered)
        if self.consecutive_bad > self.max_bad_steps:
            self.rollbacks += 1
            raise RollbackSignal(
                first_iteration, self.consecutive_bad, fault_id=fault_id,
                bad_tag=bad_tag,
            )
        emit_recovery(
            "recovery_skip_step", site="train_step", fault_id=fault_id,
            iteration=first_iteration, iterations=covered,
            consecutive_bad=self.consecutive_bad,
            budget=self.max_bad_steps, bad_tag=bad_tag,
        )
        logger.warning(
            "non-finite loss at super-step %d (losses=%s, first offending "
            "tag=%s); skipped (%d/%d bad before rollback)",
            first_iteration, losses, bad_tag, self.consecutive_bad,
            self.max_bad_steps,
        )
        return False


# ---------------------------------------------------------------------------
# checkpoint integrity: digest sidecar + validated fallback restore

DIGEST_SIDECAR = "digest.json"


def state_digest(host_state) -> str:
    """sha256 over the host state pytree: every leaf's key path, shape,
    dtype, and raw bytes, in deterministic tree order. Computed on the
    SAME host snapshot the commit writes, so a byte-level mismatch at
    restore means the artifact (not the digest) changed."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    leaves, _ = jax.tree_util.tree_flatten_with_path(host_state)
    for path, leaf in leaves:
        arr = np.asarray(leaf)
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def write_digest(path: str, digest: str) -> None:
    """Write the ``digest.json`` sidecar (temp-then-rename, like the
    ``meta.yml`` commit marker it rides next to)."""
    import json
    import os

    sidecar = os.path.join(path, DIGEST_SIDECAR)
    tmp = sidecar + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"algo": "sha256", "digest": digest}, f)
    os.replace(tmp, sidecar)


def read_digest(path: str) -> Optional[str]:
    import json
    import os

    try:
        with open(os.path.join(path, DIGEST_SIDECAR)) as f:
            return json.load(f)["digest"]
    except (OSError, ValueError, KeyError):
        return None


def validate_restored(path: str, restored) -> Tuple[bool, str]:
    """Restore-time integrity verdict for a just-restored state pytree:

    - when a ``digest.json`` sidecar exists, the recomputed digest must
      match byte-for-byte (catches truncation/corruption Orbax silently
      tolerates);
    - every leaf must be finite (a committed checkpoint of a poisoned run
      must never become a rollback target).

    Returns ``(ok, reason)``; pre-sidecar checkpoints (older PRs) skip the
    digest half but still get the finiteness sweep."""
    import jax
    import numpy as np

    for leaf in jax.tree.leaves(restored):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(
                arr).all():
            return False, "non-finite leaf values"
    want = read_digest(path)
    if want is not None:
        got = state_digest(restored)
        if got != want:
            return False, f"digest mismatch (sidecar {want[:12]}…, " \
                          f"restored {got[:12]}…)"
    return True, "ok"


def restore_with_fallback(
    root: str,
    template,
    config: Dict,
    reset: bool = False,
):
    """Validated resume over EVERY committed checkpoint under ``root``,
    newest-first: the ``ckpt_restore`` fault site fires before the first
    attempt (a ``truncate`` spec corrupts the candidate on disk — real
    bytes, not a mock), and any candidate that fails to restore or fails
    :func:`validate_restored` is skipped with a loud warning and a
    ``recovery_restore_fallback`` event. Returns
    ``(state, start_iteration, monitor_best, path)`` — ``path`` None when
    no valid checkpoint exists (fresh start)."""
    from esr_tpu.resilience import faults
    from esr_tpu.training.checkpoint import (
        find_committed_checkpoints,
        resume_checkpoint,
        restore_state,
    )

    candidates = find_committed_checkpoints(root)
    for attempt, path in enumerate(candidates):
        for spec in faults.fire("ckpt_restore", attempt, path=path):
            if spec.kind == "truncate":
                faults.truncate_checkpoint_arrays(path)
        try:
            restored = restore_state(path, template)
            ok, reason = validate_restored(path, restored)
        except Exception as e:  # noqa: BLE001 - corrupted artifact: fall back
            ok, reason = False, repr(e)
            logger.warning(
                "checkpoint %s failed to restore (%r); trying the "
                "previous commit", path, e,
            )
        if ok:
            # hand the just-validated pytree through so the checkpoint is
            # not read from disk a second time
            state, start, best = resume_checkpoint(
                path, template, config, reset=reset, restored=restored
            )
            return state, start, best, path
        logger.error(
            "checkpoint %s failed restore-time integrity validation "
            "(%s); falling back to the previous commit", path, reason,
        )
        emit_recovery(
            "recovery_restore_fallback", site="ckpt_restore",
            path=path, reason=reason, attempt=attempt,
            remaining=len(candidates) - attempt - 1,
        )
    return template, 0, None, None


# ---------------------------------------------------------------------------
# serving circuit breaker ledger


class LaneHealth:
    """Per-lane fault accounting for the serving tier's circuit breaker.

    A lane accumulating ``quarantine_k`` faults should be drained and
    quarantined (``LaneScheduler.quarantine``); the decision itself lives
    in ``serving/server.py`` — this class is the pure, unit-testable
    ledger."""

    def __init__(self, quarantine_k: int = 3):
        if quarantine_k < 1:
            raise ValueError(
                f"quarantine_k must be >= 1, got {quarantine_k}"
            )
        self.quarantine_k = int(quarantine_k)
        self.faults: Dict[int, int] = {}

    def record(self, lane: int) -> int:
        self.faults[lane] = self.faults.get(lane, 0) + 1
        return self.faults[lane]

    def should_quarantine(self, lane: int) -> bool:
        return self.faults.get(lane, 0) >= self.quarantine_k
