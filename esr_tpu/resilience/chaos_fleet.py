"""The scripted FLEET chaos scenario: N replicas under replica-level faults.

One level up from :mod:`esr_tpu.resilience.chaos` (train/serve-site
faults inside one process), this scenario proves the FLEET contract
(docs/SERVING.md "The fleet", ISSUE 15) end to end on CPU, shared by the
tier-1 fleet smoke (``tests/test_fleet_smoke.py``,
``scripts/fleet_smoke.sh``) and the bench ``fleet_loadgen`` stage's
chaos half:

1. **twin serve** — every stream through ONE fault-free ``ServingEngine``
   (same classes, same request ids): the per-request ground truth.
2. **fleet serve** — the SAME streams as seeded Poisson traffic through a
   3-replica :class:`~esr_tpu.serving.fleet.FleetRouter` under a
   ``fleet_router`` :class:`~esr_tpu.resilience.faults.FaultPlan`:
   ``router_handoff`` (forced voluntary drain — streams migrate
   bit-exactly over the lane-state wire format), ``replica_kill``
   (abrupt death mid-run — missed heartbeats, involuntary fail-over),
   and ``replica_partition`` (unreachable — fenced, then failed over).
3. **fleet view** (ISSUE 18) — the live fleet plane
   (``obs.fleetview.start_fleet_plane``) runs THROUGH the faults: the
   router's supervisor feeds every ``/snapshot`` poll into a
   :class:`~esr_tpu.obs.fleetview.FleetAggregator` (one fetch per
   replica per poll), the router's own ledger stream joins the merge as
   a local, and the killed replica must flip STALE — excluded with an
   annotation, never silently merged — while the merged ``/slo``
   verdict stays in agreement with the offline reporter over the
   router + survivor telemetry files.
4. **checks** — zero lost requests (every ledger row classified
   terminal), all three faults injected AND recovered
   (``faults.unrecovered == 0`` over the merged router + replica
   telemetry), migrated/failed-over streams matching the twin's
   per-request metric means within ``1e-5`` rel (a handoff resumes
   bit-exactly; a fail-over replays from window 0 — either way the
   full-stream means are the twin's), the merged
   ``obs report --slo configs/slo_fleet.yml`` exiting 0, and the
   fleet-view properties above.

CLI: ``python -m esr_tpu.resilience.chaos_fleet --out DIR [--seed N]``
prints the summary JSON and exits 0 iff every acceptance property held.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

from esr_tpu.resilience.faults import FaultPlan, FaultSpec, installed

# scenario scale (tiny: the whole thing must run inside the CPU tier-1
# budget; the chunk programs are shared with the twin via the process
# program cache, so tracing is paid once)
N_REPLICAS = 3
LANES = 2
N_STREAMS = 6
RATE_HZ = 200.0        # arrival BURST: every stream is submitted (and
                       # ring-placed) before the early fault rounds land,
                       # so the kill always finds live streams to fail
                       # over — from ANY program-cache state (the PR 16
                       # burst rule: a warm cache makes rounds far faster
                       # than wall-clock arrivals, and a 2.5 Hz schedule
                       # left the killed replica empty in full-suite runs)
EVENTS_SCHEDULE = (1600, 4200)   # alternating short/long streams


def dataset_config() -> Dict:
    return {
        "scale": 2,
        "ori_scale": "down8",
        "time_bins": 1,
        "mode": "events",
        "window": 1024,
        "sliding_window": 512,
        "need_gt_events": True,
        "need_gt_frame": False,
        "data_augment": {"enabled": False, "augment": [],
                         "augment_prob": []},
        "sequence": {
            "sequence_length": 4,
            "seqn": 3,
            "step_size": None,
            "pause": {"enabled": False},
        },
    }


def serving_classes() -> Dict:
    from esr_tpu.serving import RequestClass

    return {
        "interactive": RequestClass("interactive", chunk_windows=2),
        "standard": RequestClass("standard", chunk_windows=4),
    }


def build_fleet_plan(seed: int) -> FaultPlan:
    """Three replica-level faults at EARLY router rounds (streams must
    still be in flight when each lands). Placement is structural —
    handoff first (state exists to migrate), kill next, partition last
    (its fence needs the detection window) — with seed jitter so the
    gate does not ossify around one fixed trace. Targets walk to an
    alive replica at enactment, so the three faults always hit three
    DIFFERENT fates."""
    import numpy as np

    rng = np.random.default_rng(seed)
    handoff_round = 1 + int(rng.integers(0, 2))           # 1-2
    kill_round = handoff_round + 1                         # 2-3
    partition_round = kill_round + 2 + int(rng.integers(0, 2))  # 4-6
    return FaultPlan([
        FaultSpec("fleet_router", handoff_round, "router_handoff",
                  arg=0.0),
        FaultSpec("fleet_router", kill_round, "replica_kill", arg=1.0),
        FaultSpec("fleet_router", partition_round, "replica_partition",
                  arg=2.0),
    ])


def _build_model(seed: int = 0):
    import jax
    import numpy as np

    from esr_tpu.models.esr import DeepRecurrNet

    # The flagship serving shape (basech=2), SHARED with the rest of the
    # serving suites on purpose: the chunk program cache is process-global
    # and keyed by (model, lanes, W, grid), so in tier-1 the tracing is
    # paid once per session (tests/conftest.py ``warmed_programs``).
    # PR 15 had to diverge to basech=4 because test_serve_smoke's churn
    # assertions only held from a cold cache; its arrival schedule is now
    # a burst that preempts deterministically from any cache state.
    model = DeepRecurrNet(inch=2, basech=2, num_frame=3)
    x = np.zeros((1, 3, 16, 16, 2), np.float32)
    params = model.init(
        jax.random.PRNGKey(seed), x, model.init_states(1, 16, 16)
    )
    return model, params


def _run_twin(out_dir: str, model, params, schedule) -> Tuple[Dict, Dict]:
    """Every stream through one fault-free engine with the SAME request
    ids and classes the fleet will see; returns ``(per-request reports,
    session summary)`` — the ground truth AND the single-engine
    baseline row the bench stage compares against."""
    from esr_tpu.obs import TelemetrySink, set_active_sink
    from esr_tpu.serving import ServingEngine

    sink = TelemetrySink(os.path.join(out_dir, "telemetry_twin.jsonl"))
    prev = set_active_sink(sink)
    try:
        engine = ServingEngine(
            model, params, dataset_config(), lanes=LANES,
            classes=serving_classes(), default_class="standard",
            preempt_quantum=0,
        )
        for a in schedule:
            engine.submit(a.path, a.request_class, request_id=a.request_id)
        summary = engine.run(max_wall_s=300.0)
        return engine.reports(), summary
    finally:
        set_active_sink(prev)
        sink.close()


def _metric_parity(twin_reports: Dict, fleet_reports: Dict) -> Dict:
    """Worst per-request relative difference of the engine-schema metric
    means between the unfaulted twin and the fleet's terminal reports —
    the migrated/failed-over parity evidence."""
    from esr_tpu.inference.engine import METRIC_KEYS

    worst = 0.0
    worst_at: Optional[Tuple[str, str]] = None
    compared = 0
    windows_match = True
    for rid, fleet_rep in fleet_reports.items():
        if fleet_rep.get("status") != "ok":
            continue
        twin_rep = twin_reports[rid]
        if fleet_rep["n_windows"] != twin_rep["n_windows"]:
            # a migrated/failed-over stream must still serve the FULL
            # window count — a short count is a lost-tail bug, reported
            # (not crashed) so the summary names it
            windows_match = False
        compared += 1
        for key in METRIC_KEYS:
            a, b = float(twin_rep[key]), float(fleet_rep[key])
            rel = abs(a - b) / max(abs(a), 1e-12)
            if rel > worst:
                worst, worst_at = rel, (rid, key)
    return {"max_rel_diff": worst, "at": worst_at, "compared": compared,
            "windows_match": windows_match}


def run_fleet_scenario(out_dir: str, seed: int = 0) -> Dict:
    """The whole scripted fleet scenario; returns the machine-checkable
    summary (every acceptance property precomputed as a boolean)."""
    from esr_tpu.obs import LiveAggregator, TelemetrySink, set_active_sink
    from esr_tpu.obs.fleetview import FleetAggregator, start_fleet_plane
    from esr_tpu.obs.report import report_files
    from esr_tpu.serving import (
        FleetRouter,
        Replica,
        poisson_schedule,
        make_stream_corpus,
    )
    from esr_tpu.serving.fleet import ReplicaSupervisor

    os.makedirs(out_dir, exist_ok=True)
    paths = make_stream_corpus(
        os.path.join(out_dir, "streams"), n=N_STREAMS, seed=seed,
        events_schedule=EVENTS_SCHEDULE,
    )
    schedule = poisson_schedule(
        paths, rate_hz=RATE_HZ, seed=seed,
        classes=("standard", "interactive"),
    )
    model, params = _build_model(seed)
    twin_reports, twin_summary = _run_twin(out_dir, model, params, schedule)

    plan = build_fleet_plan(seed)
    replica_files = {
        f"r{i}": os.path.join(out_dir, f"telemetry_r{i}.jsonl")
        for i in range(N_REPLICAS)
    }
    live_slo = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "configs", "slo.yml",
    )
    replicas = [
        Replica(
            rid, model, params, dataset_config(),
            telemetry_path=path, classes=serving_classes(),
            default_class="standard", lanes=LANES,
            live_slo=live_slo,
            preempt_quantum=0,
        ).start()
        for rid, path in sorted(replica_files.items())
    ]
    router_file = os.path.join(out_dir, "telemetry_router.jsonl")
    router_sink = TelemetrySink(router_file)
    prev = set_active_sink(router_sink)
    # the live fleet view (ISSUE 18, docs/OBSERVABILITY.md "The fleet
    # view") runs THROUGH the faults: the router's supervisor hands each
    # /snapshot poll to the FleetAggregator (one fetch per replica per
    # poll serves death detection AND the merge), and the router's own
    # ledger stream joins as a local
    router_agg = LiveAggregator().attach(router_sink)
    fleet_agg = FleetAggregator(scrape_budget=2)
    fleet_agg.attach_local("router", router_agg)
    router = FleetRouter(
        replicas, default_class="standard",
        failover_budget=2, miss_budget=2,
        supervisor=ReplicaSupervisor(
            miss_budget=2, observer=fleet_agg.ingest),
    )
    fleet_plane = start_fleet_plane(
        replicas, port=0, slo_path=live_slo, fleet=fleet_agg,
        topology=lambda: {"ring_ownership": router.ring.ownership()},
    )
    t0 = time.monotonic()
    fleet_view: Optional[Dict] = None
    fleet_slo: Optional[Dict] = None
    try:
        with installed(plan):
            summary = router.run(arrivals=schedule, max_wall_s=300.0)
        # one final pull so the merged view covers every survivor's
        # full run, then capture the fleet documents while the
        # survivors' planes are still up
        fleet_agg.scrape_once()
        fleet_view = fleet_plane.server.fleet_doc()
        _, fleet_slo = fleet_plane.server.slo_doc()
    finally:
        fleet_plane.close()
        router.close()
        set_active_sink(prev)
        router_sink.close()
    wall = time.monotonic() - t0

    fleet_reports = router.reports()
    parity = _metric_parity(twin_reports, fleet_reports)
    merged_args = [f"router={router_file}"] + [
        f"{rid}={path}" for rid, path in sorted(replica_files.items())
    ]
    slo_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "configs", "slo_fleet.yml",
    )
    merged_doc, merged_code = report_files(
        merged_args, slo_path,
        out_path=os.path.join(out_dir, "FLEET_REPORT.json"),
    )
    faults = merged_doc["report"]["faults"]

    # the offline side of the fleet-view agreement: the SAME SLO file
    # the live fleet /slo evaluated, applied offline to the router +
    # SURVIVOR telemetry (the dead replicas are stale-excluded from the
    # live merge, so their files are excluded here too)
    dead = sorted(rid for rid, state in summary["replicas"].items()
                  if state == "dead")
    survivor_args = [f"router={router_file}"] + [
        f"{rid}={path}" for rid, path in sorted(replica_files.items())
        if rid not in dead
    ]
    _survivor_doc, survivor_code = report_files(
        survivor_args, live_slo,
        out_path=os.path.join(out_dir, "FLEET_VIEW_REPORT.json"),
    )

    statuses = {r["status"] for r in fleet_reports.values()}
    result = {
        "seed": seed,
        "wall_s": round(wall, 3),
        "summary": summary,
        "twin_summary": twin_summary,
        "parity": parity,
        "faults": faults,
        "merged_report": os.path.join(out_dir, "FLEET_REPORT.json"),
        "fleet_view": fleet_view,
        "fleet_slo": fleet_slo,
        "telemetry": {
            "router": router_file, **replica_files,
            "twin": os.path.join(out_dir, "telemetry_twin.jsonl"),
        },
        "checks": {
            # zero lost requests: every submitted request classified
            "zero_lost": bool(summary["zero_lost"]),
            "all_statuses_classified": None not in statuses,
            # all three fleet faults fired (a drained-too-early run
            # proves nothing) and every one was answered
            "all_faults_fired": plan.pending_count() == 0,
            "enough_faults": faults["injected"] >= 3,
            "all_faults_recovered": faults["unrecovered"] == 0,
            # migration AND fail-over genuinely happened
            "migrated": summary["migrations"] >= 1,
            "failed_over": summary["failovers"] >= 1,
            # a replica really died and one was really fenced
            "replica_died": "dead" in summary["replicas"].values(),
            # per-request metric parity with the unfaulted twin
            "twin_parity": (parity["max_rel_diff"] <= 1e-5
                            and parity["windows_match"]
                            and parity["compared"] >= 1),
            "all_requests_ok": all(
                r["status"] == "ok" for r in fleet_reports.values()
            ),
            # the merged fleet SLO gate (configs/slo_fleet.yml) is green
            "merged_slo_ok": merged_code == 0,
            # ISSUE 18: the live fleet view ran THROUGH the faults —
            # every dead replica flipped STALE and was excluded with an
            # annotation (never silently merged) ...
            "fleet_killed_stale": (
                fleet_view is not None and bool(dead) and all(
                    fleet_view["replicas"][rid]["stale"]
                    and rid in fleet_view["excluded"]
                    for rid in dead
                )
            ),
            # ... every survivor (and the router's own ledger stream)
            # made it INTO the final merge ...
            "fleet_survivors_merged": (
                fleet_view is not None
                and "local:router" in fleet_view["merged"]
                and all(rid in fleet_view["merged"]
                        for rid in sorted(replica_files)
                        if rid not in dead)
            ),
            # ... and the merged live /slo verdict agrees with the
            # offline reporter over the router + survivor files
            "fleet_slo_matches_offline": (
                fleet_slo is not None
                and (fleet_slo["verdict"] == "ok") == (survivor_code == 0)
            ),
        },
    }
    result["ok"] = all(result["checks"].values())
    return result


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="scripted fleet chaos scenario (docs/SERVING.md "
                    "'The fleet')"
    )
    p.add_argument("--out", required=True, help="output directory")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    summary = run_fleet_scenario(args.out, seed=args.seed)
    with open(os.path.join(args.out, "FLEET_CHAOS_SUMMARY.json"),
              "w") as f:
        json.dump(summary, f, indent=2, default=str)
    print(json.dumps({
        "ok": summary["ok"],
        "checks": summary["checks"],
        "statuses": summary["summary"]["statuses"],
        "migrations": summary["summary"]["migrations"],
        "failovers": summary["summary"]["failovers"],
        "parity_max_rel_diff": summary["parity"]["max_rel_diff"],
        "faults": {k: summary["faults"][k]
                   for k in ("injected", "recovered", "unrecovered")},
    }))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
