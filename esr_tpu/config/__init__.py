"""Config system: YAML parsing, CLI overrides, run dirs, component builders."""

from esr_tpu.config.parser import (
    RunConfig,
    apply_overrides,
    load_config,
    set_by_path,
)
from esr_tpu.config.build import (
    build_lr_schedule,
    build_model,
    build_optimizer,
    build_train_loader,
)

__all__ = [
    "RunConfig",
    "apply_overrides",
    "load_config",
    "set_by_path",
    "build_lr_schedule",
    "build_model",
    "build_optimizer",
    "build_train_loader",
]
