"""One precision policy for train / valid / infer / serve.

The ladder's rungs are *config-named* (VirtualFlow, arxiv 2009.09523:
one YAML runs identically from CPU smoke to pod slice), so every
entrypoint resolves the SAME spelling through the SAME precedence:

    explicit CLI flag  >  checkpoint config (``trainer.precision``)  >
    built-in default (``f32``)

``trainer.precision`` historically applied only to the train step;
``inference.engine``/``ServingEngine`` silently ran f32 regardless of
how the checkpoint was trained. This module is the single seam all four
planes import, so a checkpoint trained at ``bf16`` serves at ``bf16``
unless the operator overrides it at the CLI.

Also owns dtype-alias canonicalization: user-facing knobs accept the
short spellings (``bf16``, ``f32``) that ``jnp.dtype`` does not
understand, while numerics code wants a numpy-parsable name. jax-free
at module scope (the obs drift harness imports it before choosing a
backend).
"""

from __future__ import annotations

from typing import Any, Optional

#: the config-level rungs — ``trainer.precision`` / ``--precision`` values.
#: ``int8`` is the SERVING rung (post-training quantization,
#: ``esr_tpu.config.quantize``): inference/serving only — the trainer
#: rejects it loudly (training updates need float params).
PRECISIONS = ("f32", "bf16", "int8")

# short/long spellings -> canonical rung name. "w8a8" is the literature
# spelling (8-bit weights, 8-bit activations) of the same PTQ rung.
_PRECISION_ALIASES = {
    "f32": "f32",
    "fp32": "f32",
    "float32": "f32",
    "bf16": "bf16",
    "bfloat16": "bf16",
    "int8": "int8",
    "i8": "int8",
    "w8a8": "int8",
}

# short/long spellings -> numpy-parsable dtype name (jnp.dtype-safe)
_DTYPE_ALIASES = {
    "bf16": "bfloat16",
    "bfloat16": "bfloat16",
    "f16": "float16",
    "fp16": "float16",
    "half": "float16",
    "float16": "float16",
    "f32": "float32",
    "fp32": "float32",
    "float32": "float32",
    "f64": "float64",
    "fp64": "float64",
    "float64": "float64",
    "int8": "int8",
    "i8": "int8",
    "w8a8": "int8",
}


def canonical_dtype(name: Any) -> str:
    """Normalize a user-facing dtype spelling to a numpy-parsable name.

    ``canonical_dtype("bf16") == "bfloat16"`` — the drift harness and
    every ``--dtype`` knob accept the short config spellings without
    each call site re-learning that ``jnp.dtype("bf16")`` raises.
    Unknown names raise ``ValueError`` with the accepted spellings.
    """
    key = str(name).strip().lower()
    if key not in _DTYPE_ALIASES:
        raise ValueError(
            f"unknown dtype {name!r}; accepted spellings: "
            f"{sorted(set(_DTYPE_ALIASES))}"
        )
    return _DTYPE_ALIASES[key]


def canonical_precision(name: Any) -> str:
    """Normalize a precision spelling to its config rung (``f32``/``bf16``)."""
    key = str(name).strip().lower()
    if key not in _PRECISION_ALIASES:
        raise ValueError(
            f"unknown precision {name!r}; supported rungs: {PRECISIONS}"
        )
    return _PRECISION_ALIASES[key]


def resolve_precision(
    cli: Optional[str] = None,
    config: Optional[str] = None,
    default: str = "f32",
) -> str:
    """Resolve one precision rung: CLI > checkpoint config > default.

    Mirrors the tri-state knob idiom (``--engine``/``--compile_cache``):
    an omitted CLI flag (``None``) defers to the checkpoint config's
    ``trainer.precision``, which defers to the built-in default. Every
    spelling is validated — a typo'd rung fails loudly at resolution,
    not as a silent f32 fallback three layers down.
    """
    for source in (cli, config, default):
        if source is not None:
            return canonical_precision(source)
    return canonical_precision(default)


def compute_dtype_of(precision: Optional[str]):
    """Map a precision rung to the ``compute_dtype`` the step factories
    take: ``None`` for f32 (the unmodified reference program) or
    ``jnp.bfloat16``. Accepts ``None`` (meaning: unresolved -> f32).

    ``int8`` also maps to ``None`` — deliberately. The PTQ rung never
    casts params/states/inputs (quantization happens INSIDE the
    contraction seams, ``esr_tpu.config.quantize``; everything between
    seams stays f32), so any caller that would cast to a compute dtype
    must not cast at all. The rung itself is threaded separately
    (``make_chunk_fn(..., precision=...)``).
    """
    if precision is None:
        return None
    rung = canonical_precision(precision)
    if rung in ("f32", "int8"):
        return None
    import jax.numpy as jnp

    return jnp.bfloat16
