"""Registry-driven component construction from config blocks.

The explicit-registry replacement for the reference's
``eval(config['...']['name'])(**args)`` instantiation
(``train_ours_cnt_seq.py:762,779,782``).

Config schema mirrors ``config/train_ours_enfssyn.yml``:

- ``model: {name, args}`` → :func:`build_model` via the model registry;
- ``optimizer: {name, args: {lr, weight_decay, amsgrad}}`` +
  ``lr_scheduler: {name, args: {gamma}}`` + the trainer's ``lr_change_rate``
  → ONE optax chain. The reference's gated scheduler stepping
  (``ExponentialLR`` every ``lr_change_rate`` iters while lr ≥ 1e-4,
  ``train_ours_cnt_seq.py:322-325``) becomes a pure schedule function — same
  lr trajectory, no mutable scheduler object;
- ``train_dataloader`` / ``valid_dataloader`` blocks → :class:`SequenceLoader`
  with per-host sharding.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import optax

from esr_tpu.data.loader import ConcatSequenceDataset, SequenceLoader
from esr_tpu.models.registry import get_model
from esr_tpu.training.optim import make_optimizer
from esr_tpu.training.schedule import exponential_with_floor

LR_FLOOR = 1e-4  # the reference's hard-coded gate (train_ours_cnt_seq.py:324)


def build_model(model_cfg: Dict):
    """``{name, args}`` → registered Flax module."""
    return get_model(model_cfg["name"], **(model_cfg.get("args") or {}))


def build_lr_schedule(
    optimizer_cfg: Dict,
    scheduler_cfg: Optional[Dict],
    lr_change_rate: Optional[int],
) -> Callable:
    """Schedule fn reproducing the reference's gated ExponentialLR."""
    base_lr = float(optimizer_cfg.get("args", {}).get("lr", 1e-3))
    if scheduler_cfg is None or lr_change_rate is None:
        return lambda step: base_lr
    name = scheduler_cfg["name"]
    if name != "ExponentialLR":
        raise KeyError(f"unknown lr_scheduler '{name}'")
    gamma = float(scheduler_cfg.get("args", {}).get("gamma", 0.95))
    return exponential_with_floor(
        base_lr, gamma=gamma, change_rate=int(lr_change_rate), floor=LR_FLOOR
    )


def build_optimizer(
    optimizer_cfg: Dict,
    scheduler_cfg: Optional[Dict] = None,
    lr_change_rate: Optional[int] = None,
) -> Tuple[optax.GradientTransformation, Callable]:
    """Optimizer + its schedule fn (returned separately so the trainer can log
    the current lr, reference ``:244-248``)."""
    args = dict(optimizer_cfg.get("args") or {})
    schedule = build_lr_schedule(optimizer_cfg, scheduler_cfg, lr_change_rate)
    opt = make_optimizer(
        optimizer_cfg["name"],
        lr=schedule,
        weight_decay=float(args.get("weight_decay", 0.0)),
        amsgrad=bool(args.get("amsgrad", False)),
        betas=tuple(args.get("betas", (0.9, 0.999))),
        eps=float(args.get("eps", 1e-8)),
    )
    return opt, schedule


def build_train_loader(
    loader_cfg: Dict,
    shard_id: int = 0,
    num_shards: int = 1,
    seed: int = 0,
) -> SequenceLoader:
    """``train_dataloader``/``valid_dataloader`` block → sharded loader.

    ``use_ddp`` from the reference schema is accepted and ignored — sharding
    is always on and is a no-op at ``num_shards=1``.
    """
    dataset = ConcatSequenceDataset.from_datalist(
        loader_cfg["path_to_datalist_txt"], loader_cfg["dataset"]
    )
    return SequenceLoader(
        dataset,
        batch_size=int(loader_cfg["batch_size"]),
        shard_id=shard_id,
        num_shards=num_shards,
        shuffle=bool(loader_cfg.get("shuffle", True)),
        drop_last=bool(loader_cfg.get("drop_last", True)),
        seed=seed,
        prefetch=int(loader_cfg.get("prefetch", 2)),
        # torch DataLoader's num_workers analogue: >0 spawns a process pool
        # for the GIL-bound windowing/augment/collate work
        num_workers=int(loader_cfg.get("num_workers", 0)),
    )
