"""YAML config parsing, semicolon key-path CLI overrides, run directories.

Rebuilds the reference's ``YAMLParser`` (``config/parser.py:14-128``):

- YAML (with anchors) loaded via ``yaml.safe_load``;
- CLI overrides addressed by semicolon key paths
  (``trainer;iteration_based_train;iterations``), reference ``:103-107``;
- run dirs ``<output>/models/<experiment>/<runid>`` and
  ``<output>/logs/<experiment>/<runid>`` with the *effective* config dumped to
  the model dir (``:22-36``); run id defaults to a timestamp (``:26-27``);
- logging configured into the log dir.

Component instantiation lives in :mod:`esr_tpu.config.build` — an explicit
registry, never ``eval`` (the reference instantiates via
``eval(config['model']['name'])``, ``train_ours_cnt_seq.py:762``; SURVEY.md §5
calls for a registry instead).
"""

from __future__ import annotations

import os
from datetime import datetime
from typing import Dict, List, Optional, Sequence, Tuple

import yaml

from esr_tpu.utils.logging import setup_logging


def load_config(path: str) -> Dict:
    with open(path) as f:
        return yaml.safe_load(f)


def dump_config(config: Dict, path: str) -> None:
    with open(path, "w") as f:
        yaml.safe_dump(config, f, sort_keys=False)


def set_by_path(tree: Dict, keypath: str, value) -> None:
    """``set_by_path(cfg, 'a;b;c', v)`` → ``cfg['a']['b']['c'] = v``
    (reference ``config/parser.py:103-107``). Intermediate dicts are created
    when absent so overrides can introduce optional blocks."""
    keys = keypath.split(";")
    node = tree
    for k in keys[:-1]:
        node = node.setdefault(k, {})
    node[keys[-1]] = _parse_scalar(value)


def _parse_scalar(value):
    """CLI strings → YAML scalars ('1e-3' → float, 'true' → bool, ...).

    YAML 1.1 only floats exponents written with a dot ('1.0e-3'); fall back to
    python float parsing so bare '1e-3' works from the CLI.
    """
    if not isinstance(value, str):
        return value
    parsed = yaml.safe_load(value)
    if isinstance(parsed, str):
        try:
            return float(parsed)
        except ValueError:
            return parsed
    return parsed


def apply_overrides(config: Dict, overrides: Sequence[str]) -> Dict:
    """Apply ``key;path=value`` strings in order (later wins)."""
    for ov in overrides:
        if "=" not in ov:
            raise ValueError(f"override {ov!r} is not of the form key;path=value")
        keypath, value = ov.split("=", 1)
        set_by_path(config, keypath, value)
    return config


class RunConfig:
    """Effective config + run directories + logging for one training run.

    dict-style item access proxies the config (reference ``parser.py:82-84``).
    """

    def __init__(
        self,
        config: Dict,
        runid: Optional[str] = None,
        resume: Optional[str] = None,
        reset: bool = False,
        seed: int = 123,
        make_dirs: bool = True,
        is_main: bool = True,
    ):
        self.config = config
        self.resume = resume
        self.reset = reset
        self.seed = seed
        self.runid = runid or datetime.now().strftime(r"%m%d_%H%M%S")

        out = config["trainer"]["output_path"]
        exp = config["experiment"]
        self.save_dir = os.path.join(out, "models", exp, self.runid)
        self.log_dir = os.path.join(out, "logs", exp, self.runid)
        if make_dirs:
            os.makedirs(self.save_dir, exist_ok=True)
            os.makedirs(self.log_dir, exist_ok=True)
            dump_config(config, os.path.join(self.save_dir, "config.yml"))
            setup_logging(self.log_dir, is_main=is_main)

    @classmethod
    def from_args(
        cls,
        config_path: str,
        overrides: Sequence[str] = (),
        runid: Optional[str] = None,
        resume: Optional[str] = None,
        reset: bool = False,
        seed: int = 123,
        make_dirs: bool = True,
        is_main: bool = True,
    ) -> "RunConfig":
        config = apply_overrides(load_config(config_path), overrides)
        return cls(config, runid, resume, reset, seed, make_dirs, is_main)

    def __getitem__(self, name: str):
        return self.config[name]

    def get(self, name: str, default=None):
        return self.config.get(name, default)
