"""Post-training int8 quantization — the ladder's serving rung.

The bf16 rung (docs/PERF.md "precision ladder") casts operands and
widens accumulators; this module is the next rung down: **w8a8 PTQ** at
the contraction seams only. Nothing outside a conv/dot changes width —
params, recurrent lane states, the rasterized wire, and every
inter-layer activation stay f32 (so ``transfer_dtype: auto`` composes
trivially: the wire carries the rasterized input dtype, quantization
happens at the seams, not on the wire). At each seam:

- **weights**: per-output-channel symmetric int8 — ``scale_c =
  max|w[..., c]| / 127``, one scale per output feature, so a channel
  with small weights does not burn its 8 bits on another channel's
  range (the standard PTQ choice, e.g. arxiv 2107.02547's fixed-point
  DCN datapath);
- **activations**: dynamic per-tensor symmetric int8 — the scale comes
  from the live ``max|x|`` in-graph, so no baked range can be stale;
- **contraction**: int8 x int8 with an **i32 accumulator**
  (``preferred_element_type=jnp.int32``) — the JX001 contract; the
  jaxpr auditor's ``flops_by_dtype`` shows these as an
  ``int8->int32`` bucket, and a narrow (int8) accumulator anywhere
  fails ``python -m esr_tpu.analysis --jaxpr``;
- **dequantize at the seam**: ``i32 * (scale_x * scale_w[c])`` back to
  the incoming float dtype, so downstream code is byte-identical to
  the f32 program.

The trigger is a TRACE-TIME scope (:func:`int8_scope`), queried by the
existing ``wide_accum_conv_general_dilated`` /
``wide_accum_dot_general`` injection seams in ``models.layers`` — the
same seam set the bf16 rung rides, so coverage is identical by
construction. The scope must be entered INSIDE the traced function
body (``make_chunk_fn`` does this when built with ``precision="int8"``)
so shape-driven retraces re-apply it.

**Calibration** (:func:`calibrate_ranges`) runs a seeded synthetic
corpus through the EXISTING ``obs/numerics`` tensor-stats taps
(``numerics_mode="stats"``, ``max_abs`` per tag) — no new
instrumentation plane. Dynamic per-tensor quantization needs no baked
ranges to run; the calibration pass records, deterministically from
its seed, the per-layer ranges the dynamic scales will encounter — the
range evidence the drift harness (``python -m esr_tpu.obs drift
--dtype int8``) and the bench quality cell attribute error against.

jax is imported lazily (module scope stays jax-free, like
``config.precision`` — the drift CLI imports before backend choice).
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Dict, Optional

# floor for the symmetric scale so an all-zero tensor quantizes to
# zeros instead of dividing by zero
_SCALE_EPS = 1e-12

# trace-time switch the models.layers seams query; a ContextVar (not a
# bare global) so concurrent traces on different threads — the serving
# pump vs a background export — cannot leak the rung into each other
_INT8_SCOPE: contextvars.ContextVar = contextvars.ContextVar(
    "esr_int8_scope", default=False
)


@contextlib.contextmanager
def int8_scope(enabled: bool = True):
    """While active, every ``models.layers`` contraction seam traced on
    this thread runs the PTQ path. Enter it INSIDE the traced function
    body (not around a ``jit`` call site) so retraces re-apply it."""
    token = _INT8_SCOPE.set(bool(enabled))
    try:
        yield
    finally:
        _INT8_SCOPE.reset(token)


def int8_enabled() -> bool:
    """Is the PTQ scope active on this thread (trace-time query)?"""
    return bool(_INT8_SCOPE.get())


# ---------------------------------------------------------------------------
# quantize / dequantize primitives


def quantize_symmetric(x, axis: Optional[int] = None):
    """Symmetric int8 quantization: ``(q, scale)`` with ``q = clip(
    round(x / scale), -127, 127)`` as int8 and ``scale`` f32.

    ``axis=None`` is per-tensor (one scalar scale — the dynamic
    activation path); ``axis=k`` is per-channel along axis ``k`` (the
    weight path: ``scale`` keeps a keepdims shape so it broadcasts
    against ``x``). Values exactly on the ``scale * [-127, 127]`` grid
    round-trip exactly (pinned by tests/test_quantize.py)."""
    import jax.numpy as jnp

    x = jnp.asarray(x)
    if axis is None:
        amax = jnp.max(jnp.abs(x))
    else:
        red = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
        amax = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    scale = (jnp.maximum(amax, _SCALE_EPS) / 127.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def dequantize(q, scale):
    """Inverse of :func:`quantize_symmetric` (f32 out): ``q * scale``."""
    import jax.numpy as jnp

    return q.astype(jnp.float32) * scale


# ---------------------------------------------------------------------------
# the quantized contractions (called by the models.layers seams)


def quantized_conv_general_dilated(lhs, rhs, window_strides, padding, **kw):
    """The PTQ conv seam: dynamic per-tensor activation quant,
    per-output-channel weight quant, int8 contraction with an i32
    ``preferred_element_type`` accumulator, dequantized back to the
    incoming float dtype. Signature mirrors flax's
    ``conv_general_dilated`` injection callable."""
    import jax
    import jax.numpy as jnp

    dn = jax.lax.conv_dimension_numbers(
        lhs.shape, rhs.shape, kw.get("dimension_numbers")
    )
    q_lhs, s_lhs = quantize_symmetric(lhs)
    # rhs_spec[0] is the output-feature dim of the kernel (HWIO -> O)
    q_rhs, s_rhs = quantize_symmetric(rhs, axis=dn.rhs_spec[0])
    acc = jax.lax.conv_general_dilated(
        q_lhs, q_rhs, window_strides, padding,
        **{**kw, "preferred_element_type": jnp.int32},
    )
    # broadcast the per-channel weight scale over the conv OUTPUT's
    # feature dim (out_spec[1] — NHWC -> C)
    shape = [1] * acc.ndim
    shape[dn.out_spec[1]] = acc.shape[dn.out_spec[1]]
    ch_scale = jnp.reshape(s_rhs, shape)
    return (acc.astype(jnp.float32) * (s_lhs * ch_scale)).astype(lhs.dtype)


def quantized_dot_general(lhs, rhs, dimension_numbers, **kw):
    """The PTQ dot seam (``nn.Dense``: rhs is ``(in, out)``, contraction
    over axis 0, output feature last) — int8 operands, i32 accumulator,
    per-output-channel dequant."""
    import jax
    import jax.numpy as jnp

    (lc, rc), (lb, rb) = dimension_numbers
    out_axes = [
        a for a in range(rhs.ndim) if a not in tuple(rc) + tuple(rb)
    ]
    q_lhs, s_lhs = quantize_symmetric(lhs)
    q_rhs, s_rhs = quantize_symmetric(rhs, axis=out_axes[-1])
    acc = jax.lax.dot_general(
        q_lhs, q_rhs, dimension_numbers,
        **{**kw, "preferred_element_type": jnp.int32},
    )
    # dot_general output layout: batch dims, lhs free dims, rhs free
    # dims — the rhs output feature lands last
    shape = [1] * acc.ndim
    shape[-1] = acc.shape[-1]
    ch_scale = jnp.reshape(s_rhs, shape)
    return (acc.astype(jnp.float32) * (s_lhs * ch_scale)).astype(lhs.dtype)


# ---------------------------------------------------------------------------
# calibration: seeded corpus -> per-layer ranges via the EXISTING taps


def calibrate_ranges(
    model=None,
    *,
    inch: int = 2,
    basech: int = 8,
    hw: int = 32,
    frames: int = 3,
    batch: int = 1,
    seed: int = 0,
    n_batches: int = 2,
) -> Dict[str, float]:
    """Per-layer activation ranges ``{tag: max_abs}`` from a seeded
    synthetic corpus pass through the numerics plane's tensor-stats
    probes (``ops.numerics.probe`` in ``mode="stats"``) — the existing
    instrumentation, no new taps. Deterministic from ``seed`` (pinned):
    params init and every corpus batch derive from it.

    ``model`` (when given) must be probe-enabled
    (``numerics=True, numerics_mode="stats"``); by default a
    ``DeepRecurrNet`` at the drift harness's geometry is built."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from esr_tpu.ops.numerics import STAT_FIELDS, flatten_probes

    if model is None:
        from esr_tpu.models.esr import DeepRecurrNet

        model = DeepRecurrNet(
            inch=inch, basech=basech, num_frame=frames,
            numerics=True, numerics_mode="stats",
        )
    states = model.init_states(batch, hw, hw)
    x0 = jax.random.normal(
        jax.random.PRNGKey(seed), (batch, frames, hw, hw, inch),
        jnp.float32,
    )
    variables = model.init(jax.random.PRNGKey(seed + 1), x0, states)
    params = {"params": variables["params"]}
    idx = STAT_FIELDS.index("max_abs")
    probes = []
    for i in range(int(n_batches)):
        x = jax.random.normal(
            jax.random.PRNGKey(seed + 2 + i),
            (batch, frames, hw, hw, inch), jnp.float32,
        )
        (_out, _st), mut = model.apply(
            params, x, states, train=False, mutable=["numerics"]
        )
        probes.append(mut["numerics"])
    ranges: Dict[str, float] = {}
    # one host transfer for the whole corpus, after the device loop
    host_probes = jax.device_get(probes)
    for taps in (flatten_probes(t) for t in host_probes):
        for tag, vec in taps.items():
            v = float(np.asarray(vec, np.float64).reshape(-1)[idx])
            ranges[tag] = max(ranges.get(tag, 0.0), v)
    return {tag: round(v, 6) for tag, v in sorted(ranges.items())}
