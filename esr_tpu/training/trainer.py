"""The training driver: iteration-based loop, validation, early stop, ckpts.

Rebuilds the reference ``Trainer`` (``train_ours_cnt_seq.py:88-341``) around
the jit'd BPTT step, the TPU way:

- ONE compiled SPMD step per sequence (scan over windows, grads all-reduced
  over the mesh by XLA) replaces the python BPTT loop + DDP backward
  (``:206-235``); per-host loaders feed the global batch
  (``stage_batch``, the ``DistributedSampler`` analogue);
- optionally ONE compiled super-step per ``trainer.k_steps`` sequences:
  K-step fused training (``training/multistep.py``, docs/PERF.md) chains
  k train steps in a single ``lax.scan`` over a staged megabatch,
  amortizing the per-call dispatch+staging floor the r4 bench measured;
  logging/eval/checkpoint cadences snap to super-step boundaries and
  epoch tails run the plain per-step program;
- validation every ``valid_step`` iterations (``:296-314``) via the jit'd
  eval step; metrics from inside jit are already globally reduced, so the
  reference's explicit logging all-reduce (``reduce_tensor``) has no
  equivalent;
- ``min valid_loss`` monitoring with early stop
  (``eval_model_performance``, ``:383-424``);
- checkpoint every ``save_period`` and on new-best (``:316-319``); the save
  is COLLECTIVE — every process calls it (Orbax barriers internally and
  writes meta/arrays from the primary host only; do NOT re-add an is_main
  gate or multi-host saves deadlock) — resume honored in ``__init__``
  (``:172-173``);
- the LR gate lives inside the optimizer's schedule
  (``exponential_with_floor``) rather than an imperative
  ``scheduler.step()`` (``:322-325``) — same trajectory;
- epoch-based mode is deliberately NOT ported: in the reference it is legacy
  and broken (uses MinkowskiEngine with the import commented out,
  SURVEY.md §2.1 Trainer row); configs enabling it get a clear error.

Seeding policy (reference ``init_seeds`` ``:30-46``): one base seed; numpy is
seeded ``seed + process_index`` per host, the loaders derive per-sequence
generators from the base seed so augmentation is reproducible, and model init
uses ``PRNGKey(seed)`` (identical across hosts — params must agree).
"""

from __future__ import annotations

import logging
import math
import os
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from esr_tpu.config.build import (
    build_model,
    build_optimizer,
    build_train_loader,
)
from esr_tpu.config.parser import RunConfig
from esr_tpu.parallel.mesh import (
    make_mesh,
    make_parallel_train_step,
    process_shard_info,
    replicate,
    stage_batch,
)
from esr_tpu.resilience import faults as _faults
from esr_tpu.resilience.recovery import RollbackSignal
from esr_tpu.training.checkpoint import resume_checkpoint, save_checkpoint
from esr_tpu.training.train_step import (
    TrainState,
    jit_eval_step,
    make_train_step,
)
from esr_tpu.utils.trackers import MetricTracker
from esr_tpu.utils.vis_events import render_event_cnt, render_frame
from esr_tpu.utils.writer import MetricWriter

from jax.sharding import NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


def _fast_forward_groups(source, n_iters: int):
    """Deterministic data fast-forward after a rollback: consume (and
    discard) the epoch's leading groups covering ``n_iters`` already-
    trained iterations, so the replay resumes at exactly the checkpoint
    boundary seeing the same batch sequence a fault-free run would (the
    sampler is (seed, epoch)-deterministic). Checkpoints land on
    super-step boundaries, so the skip normally ends exactly on a group
    boundary; an overshoot (a checkpoint inherited from a differently-
    grouped run) resumes at the next boundary with a loud warning."""
    skipped = 0
    for group in source:
        if skipped < n_iters:
            skipped += len(group)
            if skipped > n_iters:
                logger.warning(
                    "rollback fast-forward overshot the checkpoint "
                    "boundary (%d skipped, %d targeted); resuming at the "
                    "group boundary", skipped, n_iters,
                )
            continue
        yield group


class Trainer:
    def __init__(self, run: RunConfig, mesh=None):
        self.run = run
        config = run.config
        trainer_cfg = config["trainer"]

        if trainer_cfg.get("epoch_based_train", {}).get("enabled", False):
            raise ValueError(
                "epoch_based_train is not supported (legacy/broken in the "
                "reference — SURVEY.md §2.1); use iteration_based_train"
            )
        it_cfg = trainer_cfg["iteration_based_train"]
        if not it_cfg.get("enabled", True):
            raise ValueError("iteration_based_train must be enabled")

        self.iterations = int(float(it_cfg["iterations"]))
        self.save_period = int(it_cfg.get("save_period", 10**9))
        self.train_log_step = int(it_cfg.get("train_log_step", 50))
        self.valid_step = int(it_cfg.get("valid_step", 1000))
        lr_change_rate = it_cfg.get("lr_change_rate")

        # persistent XLA compile cache (trainer.compile_cache): enabled
        # BEFORE any jit is built so every compile this run does is
        # cache-eligible. The win is the `-r auto` preemption/requeue loop:
        # a relaunched run skips recompiling programs an earlier process
        # already lowered (platform-keyed — CPU smoke entries never collide
        # with TPU entries). True = artifacts/xla_cache; a string = that
        # directory. docs/PERF.md "the serial tail".
        self.compile_cache_dir = None
        cc = trainer_cfg.get("compile_cache", False)
        if cc:
            from esr_tpu.utils.xla_cache import enable_compile_cache

            self.compile_cache_dir = enable_compile_cache(cc)

        # async checkpointing (trainer.async_checkpoint): the save's
        # blocking cost on the super-step critical path shrinks to the
        # device->host snapshot; the Orbax-arrays-then-meta.yml commit runs
        # on a background writer thread, barriered before the next
        # snapshot, the final save, and train()'s finally
        # (training/async_checkpoint.py, docs/PERF.md "the serial tail").
        self.async_checkpoint = bool(
            trainer_cfg.get("async_checkpoint", False)
        )
        # resilience knobs (docs/RESILIENCE.md). max_bad_steps: how many
        # CONSECUTIVE non-finite-loss super-steps are skipped-and-logged
        # before the anomaly guard rolls back to the last valid committed
        # checkpoint (None disables the guard — the pre-resilience
        # behavior of silently recording NaN). dispatch_retries bounds the
        # retry of a transiently failing step dispatch; commit_retries /
        # commit_backoff_s parameterize the checkpoint-commit retry;
        # prefetch_stall_timeout_s arms the DevicePrefetcher watchdog.
        self.max_bad_steps = trainer_cfg.get("max_bad_steps", None)
        self._guard = None
        if self.max_bad_steps is not None:
            from esr_tpu.resilience.recovery import AnomalyGuard

            self._guard = AnomalyGuard(int(self.max_bad_steps))
        self.max_rollbacks = int(trainer_cfg.get("max_rollbacks", 2))
        self.dispatch_retries = int(trainer_cfg.get("dispatch_retries", 1))
        if self.dispatch_retries < 0:
            raise ValueError(
                f"dispatch_retries must be >= 0, got {self.dispatch_retries}"
            )
        if self.dispatch_retries and jax.process_count() > 1:
            # the train step is collective across processes: one process
            # retrying a dispatch alone would desynchronize the others'
            # collectives and hang the fleet — single-process only until
            # a coordinated retry protocol exists (docs/RESILIENCE.md)
            logger.info(
                "dispatch_retries disabled under multi-process "
                "(collective step; %d processes)", jax.process_count()
            )
            self.dispatch_retries = 0
        self.prefetch_stall_timeout = trainer_cfg.get(
            "prefetch_stall_timeout_s", None
        )

        self._async_ckpt = None
        if self.async_checkpoint:
            from esr_tpu.training.async_checkpoint import AsyncCheckpointer

            self._async_ckpt = AsyncCheckpointer(
                commit_retries=int(trainer_cfg.get("commit_retries", 2)),
                commit_backoff_s=float(
                    trainer_cfg.get("commit_backoff_s", 0.1)
                ),
            )

        # scan-fused validation (trainer.validate): route _valid through
        # the production make_multi_step/lax.scan machinery — chunk_windows
        # eval steps fused per dispatch, metric sums accumulated ON DEVICE
        # in the scan carry, ONE host readback per validation pass instead
        # of one per batch. fused: false restores the per-batch path
        # (numerics agree to f32 accumulation order, ~1e-7 rel).
        vcfg = trainer_cfg.get("validate", {}) or {}
        self.valid_fused = bool(vcfg.get("fused", True))
        self.valid_chunk = int(vcfg.get("chunk_windows", 8))
        if self.valid_chunk < 1:
            raise ValueError(
                f"validate.chunk_windows must be >= 1, got {self.valid_chunk}"
            )
        self._eval_chunk = None
        self._eval_accum = None
        # host sync points of the most recent validation pass (fused: 1;
        # sequential: one per batch) — the bench ckpt_overlap stage and the
        # one-readback acceptance test read this
        self.last_valid_readbacks = 0

        # seeding policy
        self.shard_id, self.num_shards = process_shard_info()
        self.is_main = self.shard_id == 0
        np.random.seed(run.seed + self.shard_id)

        # data — build only the streams the loop consumes (per-key laziness
        # in EventWindowDataset is the host-throughput lever; the reference
        # rasterizes all ~17 unconditionally). A user-set item_keys wins.
        vis_cfg0 = trainer_cfg.get("vis", {}) or {}
        # device_rasterize: host ships fixed-capacity raw event windows and
        # the jit'd step scatter-adds them on chip (BASELINE's "jit'd
        # scatter-add kernels feeding the HBM-resident event tensor") —
        # minimal host work + ~50x smaller host->device transfers.
        self.device_rasterize = bool(trainer_cfg.get("device_rasterize", False))
        # dataset-level `encode: device|host` (docs/CONFIG.md): the
        # VirtualFlow-style config-named spelling of the same placement
        # decision — one YAML runs host-encoded on CPU smoke and
        # device-encoded on chip by flipping one dataset row. When set it
        # is authoritative; a contradicting trainer.device_rasterize is a
        # config error, not a silent override.
        encode = (
            config["train_dataloader"].get("dataset") or {}
        ).get("encode")
        if encode not in (None, "host", "device"):
            raise ValueError(
                f"unknown dataset encode {encode!r} ('host' or 'device')"
            )
        if encode is not None:
            want = encode == "device"
            explicit = trainer_cfg.get("device_rasterize")
            if explicit is not None and bool(explicit) != want:
                raise ValueError(
                    f"dataset encode: {encode!r} contradicts "
                    f"trainer.device_rasterize: {explicit!r}"
                )
            self.device_rasterize = want
        # one precision policy (esr_tpu.config.precision): the trainer is
        # the config-block source the CLI-less planes defer to. Resolved
        # here, BEFORE the transfer knob, so transfer_dtype: auto can
        # follow the rung.
        from esr_tpu.config.precision import (
            compute_dtype_of,
            resolve_precision,
        )

        self.precision = resolve_precision(
            config=trainer_cfg.get("precision")
        )
        if self.precision == "int8":
            # the PTQ rung is serving-side only: training needs float
            # params/grads, and "train at int8" would silently mean
            # "quantize nothing" — refuse loudly instead
            raise ValueError(
                "trainer.precision: int8 is not a training rung — int8 is "
                "post-training quantization for the inference/serving "
                "path (infer.py/serve.py --precision int8, docs/PERF.md "
                "'precision ladder'); train at f32 or bf16"
            )
        compute_dtype = compute_dtype_of(self.precision)
        # opt-in bf16 host->device batch transfer: halves the bytes the
        # count-map streams push over PCIe/ICI each TRAIN step (the e2e
        # bottleneck on transfer-bound hosts). Inputs are bf16-rounded
        # BEFORE the step (train compute already casts when
        # precision=bf16); gt rounding perturbs the train loss target by
        # <=2^-8 relative — opt-in and documented, never default.
        # Validation batches stay f32 so the 'min valid_loss' monitor,
        # best-checkpoint selection, and early stop are bit-identical to a
        # non-optioned run.
        transfer = trainer_cfg.get("transfer_dtype", None)
        if transfer not in (None, "f32", "bf16", "auto"):
            raise ValueError(f"unknown transfer_dtype {transfer!r}")
        if transfer == "auto":
            # compose with the precision rung instead of being a separate
            # train-only knob: at bf16 the step casts inputs to bf16
            # in-graph anyway, so rounding them on the host first is free
            # precision-wise and halves the wire bytes; at f32 it stays off.
            transfer = "bf16" if self.precision == "bf16" else "f32"
        self.transfer_dtype = (
            jnp.bfloat16 if transfer == "bf16" else None
        )
        if self.transfer_dtype is not None and self.device_rasterize:
            raise ValueError(
                "transfer_dtype=bf16 only applies to the count-map "
                "streams; device_rasterize already ships compact integer "
                "event windows — drop one of the two options"
            )
        if self.device_rasterize:
            train_keys = [
                "inp_norm_events", "inp_events_valid",
                "gt_raw_events", "gt_events_valid",
            ]
        else:
            train_keys = ["inp_scaled_cnt", "gt_cnt"]
        if vis_cfg0.get("enabled", False):
            train_keys += ["inp_cnt", "gt_img", "inp_scaled_cnt", "gt_cnt"]
        train_keys = list(dict.fromkeys(train_keys))

        def _loader_cfg(block, keys):
            import copy

            cfg = copy.deepcopy(block)
            cfg["dataset"].setdefault("item_keys", keys)
            # `encode:` is a trainer-resolved placement knob, not a
            # dataset-construction parameter
            cfg["dataset"].pop("encode", None)
            return cfg

        self.train_loader = build_train_loader(
            _loader_cfg(config["train_dataloader"], train_keys),
            self.shard_id,
            self.num_shards,
            seed=run.seed,
        )
        self.valid_loader = None
        if config.get("valid_dataloader") is not None:
            valid_keys = (
                train_keys[:4] if self.device_rasterize
                else ["inp_scaled_cnt", "gt_cnt"]
            )
            self.valid_loader = build_train_loader(
                _loader_cfg(config["valid_dataloader"], valid_keys),
                self.shard_id,
                self.num_shards,
                seed=run.seed,
            )

        # the numerics plane (obs v4, docs/OBSERVABILITY.md): default OFF
        # — probes change nothing (traced programs stay bitwise-identical,
        # pinned). When on, the model is built with its probe taps armed
        # (model args `numerics`), the train step reads them back through
        # the EXISTING cadence-gated metrics readback, per-tag `numerics`
        # records land in the JSONL sink at the train_log_step cadence,
        # and the anomaly guard's rollback events carry the first
        # offending tag (layer-named rollback).
        self.numerics = bool(trainer_cfg.get("numerics", False))

        # model + optimizer
        model_cfg = config["model"]
        if self.numerics:
            import copy

            model_cfg = copy.deepcopy(model_cfg)
            # `args:` may be an explicitly-empty YAML block (None) —
            # build_model tolerates that shape, so this must too
            model_cfg["args"] = {
                **(model_cfg.get("args") or {}), "numerics": True,
            }
        self.model = build_model(model_cfg)
        self.optimizer, self.schedule = build_optimizer(
            config["optimizer"], config.get("lr_scheduler"), lr_change_rate
        )
        self.seqn = int(
            config["train_dataloader"]["dataset"]["sequence"].get("seqn", 3)
        )
        self.mid_idx = (self.seqn - 1) // 2

        # mesh + compiled steps
        self.mesh = mesh if mesh is not None else make_mesh()
        remat = bool(trainer_cfg.get("remat", False))
        # precision/compute_dtype resolved above (one policy, CONFIG.md)
        rasterize = None
        if self.device_rasterize:
            from esr_tpu.training.train_step import make_device_rasterizer

            rasterize = make_device_rasterizer(self.train_loader.gt_resolution)
        self._rasterize = rasterize
        base_step = make_train_step(
            self.model, self.optimizer, self.seqn,
            remat=remat, compute_dtype=compute_dtype,
            rasterize=rasterize, numerics=self.numerics,
        )
        self.train_step = make_parallel_train_step(base_step, self.mesh)
        # K-step fusion (the r4 dispatch-floor fix): chain k_steps train
        # steps inside ONE executable via lax.scan over a staged megabatch,
        # so per-step Python dispatch + re-staging (~76.8 ms/call over the
        # tunnel vs 57.7 ms of device compute, BASELINE.md) amortizes 1/k.
        # k_steps=1 keeps the plain per-step path — identical programs,
        # identical numerics, identical cadence.
        self.k_steps = int(trainer_cfg.get("k_steps", 1))
        if self.k_steps < 1:
            raise ValueError(f"k_steps must be >= 1, got {self.k_steps}")
        self.multi_step = None
        if self.k_steps > 1:
            from esr_tpu.parallel.mesh import make_parallel_multi_step
            from esr_tpu.training.multistep import make_multi_step

            self.multi_step = make_parallel_multi_step(
                make_multi_step(base_step, self.k_steps), self.mesh
            )
        repl = NamedSharding(self.mesh, P())
        data = NamedSharding(self.mesh, P("data"))
        # retrace-guarded jit (analysis.retrace_guard): a validation-loader
        # shape leak would otherwise recompile every stamp, silently
        self._compute_dtype = compute_dtype
        self.eval_step = jit_eval_step(
            self.model, self.seqn, rasterize=rasterize,
            compute_dtype=compute_dtype,
            in_shardings=(repl, data),
            out_shardings=repl,
        )

        # params init — identical on every host
        kh, kw = self.train_loader.gt_resolution
        b = int(config["train_dataloader"]["batch_size"])
        dummy = np.zeros((1, self.seqn, kh, kw, self.model.inch), np.float32)
        states = self.model.init_states(1, kh, kw)
        params = self.model.init(jax.random.PRNGKey(run.seed), dummy, states)
        if isinstance(params, dict) and "numerics" in params:
            # model.init runs with every collection mutable, so the probe
            # taps sow one throwaway 'numerics' entry; it must not ride
            # the TrainState (checkpoints, digests, donation) — probes
            # are read back per step via mutable apply, never carried
            params = {k: v for k, v in params.items() if k != "numerics"}
        state = TrainState.create(params, self.optimizer)

        # monitor config (reference :149-157)
        self.monitor = trainer_cfg.get("monitor", "off")
        if self.monitor == "off":
            self.mnt_mode, self.mnt_metric = "off", None
            self.mnt_best = 0.0
        else:
            self.mnt_mode, self.mnt_metric = self.monitor.split()
            assert self.mnt_mode in ("min", "max")
            self.mnt_best = math.inf if self.mnt_mode == "min" else -math.inf
        self.early_stop = int(float(trainer_cfg.get("early_stop", 10**9)))
        self.not_improved_count = 0

        # observability (main process only, reference :160-169). The
        # structured telemetry sink (esr_tpu.obs, docs/OBSERVABILITY.md) is
        # the unified stream every instrumented component writes through:
        # writer/tracker scalars, per-super-step span attribution,
        # prefetcher health, and checked_jit compile events. Activated
        # process-wide so components with no Trainer reference (the
        # retrace guard, the prefetcher) find it.
        self.sink = None
        if self.is_main and bool(trainer_cfg.get("telemetry", True)):
            from esr_tpu.obs import (
                TelemetrySink,
                config_fingerprint,
                run_manifest,
            )

            self.sink = TelemetrySink(
                os.path.join(run.log_dir, "telemetry.jsonl"),
                manifest=run_manifest(
                    config_fingerprint=config_fingerprint(config)
                ),
            )
            # NOT activated here: train() installs it (and its finally
            # deactivates it), so a Trainer constructed but never trained
            # can never leak the process-active sink to unrelated runs

        # live telemetry plane (obs v3, docs/OBSERVABILITY.md): OPT-IN
        # (default off — no existing entry point changes behavior).
        # trainer.live_telemetry accepts true (ephemeral port), an int
        # port, or a mapping {port, slo, windows, rel_err}. Requires the
        # JSONL sink (the live plane runs beside it, never instead).
        lt = trainer_cfg.get("live_telemetry", False)
        self.live_cfg = None
        # identity checks, not truthiness: live_telemetry: 0 means
        # "ephemeral port", not "off"; non-main hosts run silent like the
        # sink
        if lt is not False and lt is not None and self.is_main:
            if self.sink is None:
                raise ValueError(
                    "trainer.live_telemetry requires trainer.telemetry "
                    "(the live plane taps the JSONL sink's record stream)"
                )
            if lt is True:
                lt = {}
            elif isinstance(lt, int) and not isinstance(lt, bool):
                lt = {"port": int(lt)}
            elif not isinstance(lt, dict):
                raise ValueError(
                    f"trainer.live_telemetry must be bool, port int, or a "
                    f"mapping, got {lt!r}"
                )
            self.live_cfg = {
                "port": int(lt.get("port", 0)),
                "slo": lt.get("slo"),
                "windows": tuple(lt.get("windows", (60.0, 300.0))),
                "rel_err": float(lt.get("rel_err", 0.01)),
                "watermark_interval_s": float(
                    lt.get("watermark_interval_s", 1.0)
                ),
            }
        self.live_plane = None  # set for the duration of train()
        # sink=False (not None) when telemetry is off: None would fall back
        # to the process-active sink, letting a leftover sink from another
        # run capture a trainer that explicitly opted out
        own_sink = self.sink if self.sink is not None else False
        self.writer = None
        if self.is_main:
            self.writer = MetricWriter(
                run.log_dir,
                logger,
                enable_tensorboard=bool(trainer_cfg.get("tensorboard", True)),
                sink=own_sink,
            )
        self.train_metrics = MetricTracker(
            ["train_mse_loss", "train_loss"], writer=self.writer,
            sink=own_sink,
        )
        # writerless by design (validation scalars only surface as stamp_*
        # at valid cadence) — the sink hook makes every per-batch valid
        # scalar observable without changing the writer contract
        self.valid_metrics = MetricTracker(
            ["valid_mse_loss", "valid_loss"], sink=own_sink
        )
        # span-based step-time attribution: one record per super-step at
        # the train_log_step cadence, decomposing wall into data_wait /
        # stage_megabatch / dispatch / device_step / metric_readback /
        # checkpoint + residual (obs/spans.py). The step callables are
        # wrapped OUTSIDE their jit boundary — telemetry never enters the
        # traced program (analysis rule ESR007).
        from esr_tpu.obs.spans import StepAttribution
        from esr_tpu.training.multistep import instrument_dispatch

        self._attr = StepAttribution(
            sink=self.sink, batch_size=b, log_step=self.train_log_step
        )
        self._stage_spans: Dict[int, float] = {}
        self.train_step = instrument_dispatch(self.train_step, self._attr)
        if self.multi_step is not None:
            self.multi_step = instrument_dispatch(self.multi_step, self._attr)
        vis_cfg = trainer_cfg.get("vis", {}) or {}
        self.vis_enabled = bool(vis_cfg.get("enabled", False))
        self.train_vis_step = int(vis_cfg.get("train_img_writer_num", 20))
        # how many steps' metrics may stay in flight before the host reads
        # them (input-pipeline overlap; 0 restores read-after-dispatch)
        self.train_lookahead = int(trainer_cfg.get("train_lookahead", 2))
        if self.train_lookahead < 0:
            raise ValueError(
                f"train_lookahead must be >= 0, got {self.train_lookahead}"
            )
        # host->device staging pipelined ``device_prefetch`` batches ahead
        # of the consuming step (DevicePrefetcher; 0 stages inline). The
        # other half of the input-pipeline overlap story: train_lookahead
        # defers the metrics READBACK, this overlaps the batch UPLOAD.
        self.device_prefetch = int(trainer_cfg.get("device_prefetch", 2))
        if self.device_prefetch < 0:
            raise ValueError(
                f"device_prefetch must be >= 0, got {self.device_prefetch}"
            )
        # how long DevicePrefetcher.close() waits for its producer thread
        # before declaring the (daemonic, harmless) leak with a warning
        self.prefetch_join_timeout = float(
            trainer_cfg.get("prefetch_join_timeout", 5.0)
        )
        if self.prefetch_join_timeout <= 0:
            raise ValueError(
                "prefetch_join_timeout must be > 0, got "
                f"{self.prefetch_join_timeout}"
            )

        self.profile_cfg = trainer_cfg.get("profile", {}) or {}
        # bounded on-chip capture (obs/device.py ProfilerCapture,
        # train.py --profile-steps): trace the first N super-step
        # ITERATIONS of this run and stamp a profiler_capture event with
        # the artifact dir — mutually exclusive with the run-long
        # trainer.profile hook (two open jax.profiler traces collide)
        self.profile_steps = int(trainer_cfg.get("profile_steps", 0) or 0)
        if self.profile_steps < 0:
            raise ValueError(
                f"profile_steps must be >= 0, got {self.profile_steps}"
            )
        if self.profile_steps and self.profile_cfg.get("enabled", False):
            raise ValueError(
                "trainer.profile_steps and trainer.profile.enabled are "
                "mutually exclusive (one jax.profiler trace at a time)"
            )
        self.start_iteration = 0

        # resume (reference :172-173, :687-725); "auto" = most recently saved
        # checkpoint under this experiment's model dir (preemption recovery)
        resume_path = run.resume
        if resume_path == "auto":
            from esr_tpu.training.checkpoint import find_latest_checkpoint

            exp_root = os.path.dirname(run.save_dir)
            resume_path = find_latest_checkpoint(exp_root)
            if resume_path is None:
                logger.info("auto-resume: no checkpoint found; fresh start")
            # every host must make the SAME decision — one host silently
            # fresh-starting while the rest resume breaks the replicated-
            # params invariant. Allgather-and-compare so EVERY host (incl.
            # process 0, which a one-way broadcast could never fail on)
            # raises loudly instead of stalling in later collectives.
            if self.num_shards > 1:
                from jax.experimental import multihost_utils

                mine = np.frombuffer(
                    (resume_path or "").encode()[:512].ljust(512), np.uint8
                ).copy()
                # host-sync audit: a device->host readback, but one-shot at
                # resume time (never inside the step loop) — intentional
                all_choices = np.asarray(
                    multihost_utils.process_allgather(mine)
                )
                if not (all_choices == mine[None]).all():
                    raise RuntimeError(
                        "auto-resume: hosts disagree on the checkpoint "
                        f"(this host found {resume_path!r}); put save_dir on "
                        "shared storage or pass -r <path> explicitly"
                    )
        if resume_path is not None:
            state, self.start_iteration, restored_best = resume_checkpoint(
                resume_path, state, config, reset=run.reset
            )
            if restored_best is not None:
                self.mnt_best = restored_best

        # rollback-of-last-resort target: when the anomaly guard fires
        # before ANY checkpoint committed, recovery restores the run-start
        # state. Deep-copied to HOST numpy: replicate()'s device_put can
        # alias the original buffers when the sharding already matches
        # (single-device CPU always does), and the first super-step then
        # DONATES them — a bare reference would hand the rollback a
        # deleted-array skeleton.
        self._init_state = (
            jax.tree.map(lambda x: np.array(x), state)
            if self._guard is not None else None
        )
        self.state = replicate(state, self.mesh)

    # -- helpers -----------------------------------------------------------

    def _schedule_value(self, i: int) -> float:
        """Schedule value as a host float without touching the accelerator.

        optax schedules are jnp expressions; evaluating one eagerly on the
        default (TPU) backend dispatches + syncs a tiny device computation
        every iteration inside the hot loop. Pin it to the host CPU device
        instead (falls back to the default backend if none is registered).
        """
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except Exception:  # noqa: BLE001 - no cpu platform registered
            return float(self.schedule(i))
        with jax.default_device(cpu):
            return float(self.schedule(i))

    def _select(
        self, batch: Dict[str, np.ndarray], *, for_train: bool = False
    ) -> Dict[str, np.ndarray]:
        """Select the host streams the step consumes (no device transfer).

        ``for_train`` gates the optional bf16 transfer cast: validation
        always ships f32 so the monitored metrics are unaffected."""
        if self.device_rasterize:
            return {
                "inp_events": batch["inp_norm_events"],
                "inp_valid": batch["inp_events_valid"],
                "gt_events": batch["gt_raw_events"],
                "gt_valid": batch["gt_events_valid"],
            }
        sel = {"inp": batch["inp_scaled_cnt"], "gt": batch["gt_cnt"]}
        if for_train and self.transfer_dtype is not None:
            # cast on host so the wire carries half the bytes; numpy
            # handles ml_dtypes.bfloat16 natively. Host-sync audit:
            # `v` is the loader's host numpy array, so np.asarray is a
            # free view here — NOT a device->host transfer.
            sel = {
                k: np.asarray(v).astype(self.transfer_dtype)
                for k, v in sel.items()
            }
        return sel

    def _stage(
        self, batch: Dict[str, np.ndarray], *, for_train: bool = False
    ) -> Dict:
        """Select the streams the step consumes and shard them."""
        return stage_batch(
            self._select(batch, for_train=for_train), self.mesh
        )

    def _stage_group(self, group) -> object:
        """Stage one train super-step's worth of host batches.

        A full group of ``k_steps`` batches is stacked into ONE
        ``{key: (k, B, L, ...)}`` megabatch and staged with the batch axis
        sharded (``stage_megabatch``) — a single upload the scanned
        super-step indexes on device. A shorter group (``k_steps == 1``,
        or the epoch-tail remainder) stages each batch individually for
        the single-step executable, so megabatch shapes stay static and
        the tail never forces a recompile of the scanned program.
        """
        from esr_tpu.data.loader import collate_megabatch
        from esr_tpu.parallel.mesh import stage_megabatch

        if self.k_steps > 1 and len(group) == self.k_steps:
            mega = collate_megabatch(
                [self._select(b, for_train=True) for b in group]
            )
            return stage_megabatch(mega, self.mesh)
        return [self._stage(b, for_train=True) for b in group]

    def _stage_group_timed(self, group) -> object:
        """:meth:`_stage_group` + a stage span record for the attribution.

        Runs on the DevicePrefetcher's PRODUCER thread: the elapsed staging
        time is parked under the group's id and picked up when the training
        loop consumes that group — reported as an *overlapped* span (it ran
        concurrently with earlier steps' device compute, so it does not
        count against the super-step's wall-clock identity)."""
        t0 = time.monotonic()
        staged = self._stage_group(group)
        self._stage_spans[id(group)] = time.monotonic() - t0
        return staged

    def _log_images(self, batch: Dict[str, np.ndarray], pred: np.ndarray) -> None:
        """TensorBoard qualitative dump (reference :258-293)."""
        mid = self.mid_idx
        # first sequence, middle window of the L frames for the input views
        self.writer.add_image(
            "train_inp_events_cnt",
            render_event_cnt(batch["inp_cnt"][0, mid]),
        )
        self.writer.add_image(
            "train_inp_scaled_events_cnt",
            render_event_cnt(batch["inp_scaled_cnt"][0, mid]),
        )
        self.writer.add_image(
            "train_esr_events_cnt", render_event_cnt(np.round(pred))
        )
        self.writer.add_image(
            "train_gt_events_cnt", render_event_cnt(batch["gt_cnt"][0, mid])
        )
        if "gt_img" in batch:
            self.writer.add_image(
                "train_gt_frame", render_frame(batch["gt_img"][0, mid])
            )

    def _valid(self, stamp: int) -> Dict[str, float]:
        """Full pass over the validation loader (reference ``_valid``,
        ``:541-633``). Metrics from jit are global; averaged over batches.

        Dispatches to the scan-fused path (``trainer.validate.fused``, the
        default) or the legacy per-batch path; both produce the same
        averages (identical math, f32 accumulation order differs by ~1e-7
        rel — pinned at 1e-5 by ``tests/test_trainer.py``)."""
        assert self.valid_loader is not None
        if self.valid_fused:
            return self._valid_fused(stamp)
        return self._valid_sequential(stamp)

    def _stamp_valid(self, stamp: int) -> Dict[str, float]:
        result = self.valid_metrics.result()
        if self.writer is not None:
            for k, v in result.items():
                self.writer.add_scalar(f"stamp_{k}", v, step=stamp)
        return result

    def _valid_sequential(self, stamp: int) -> Dict[str, float]:
        """The per-batch path: one eval dispatch + one host readback per
        batch (kept for A/B parity and as the ``validate.fused: false``
        fallback)."""
        self.valid_metrics.reset()
        # keep device metrics in flight: float() right after dispatch forces
        # a host round-trip per batch, serializing the pipeline. A bounded
        # lookahead (consume the oldest once 2 are pending) pipelines
        # staging with compute while keeping device residency O(1), not
        # O(len(valid_loader)).
        from collections import deque

        pending: deque = deque()
        readbacks = 0

        def drain(out):
            nonlocal readbacks
            self.valid_metrics.update("valid_loss", float(out["valid_loss"]))
            self.valid_metrics.update(
                "valid_mse_loss", float(out["valid_mse_loss"])
            )
            readbacks += 1

        for batch in self.valid_loader:
            pending.append(
                self.eval_step(self.state.params, self._stage(batch))
            )
            if len(pending) > 2:
                drain(pending.popleft())
        while pending:
            drain(pending.popleft())
        self.last_valid_readbacks = readbacks
        return self._stamp_valid(stamp)

    def _build_fused_eval(self) -> None:
        """Compile the fused validation programs (once per run).

        ``eval_chunk`` is ``chunk_windows`` eval steps chained through the
        production :func:`~esr_tpu.training.multistep.make_multi_step` /
        ``lax.scan`` machinery (the exact pattern the streaming inference
        engine ships): the carry is ``(params, metric sums)``, each scanned
        step adds its globally-reduced scalars into the sums ON DEVICE.
        ``eval_accum`` is the single-batch tail program (ragged final
        batches / short tails stay off the scanned program's static
        shapes). Neither performs a host readback; neither donates (the
        carry aliases ``self.state.params``)."""
        from esr_tpu.analysis.retrace_guard import checked_jit
        from esr_tpu.training.multistep import make_multi_step
        from esr_tpu.training.train_step import make_fused_eval_accum

        # the accumulator is the registered production program the jaxpr
        # auditor traces (esr_tpu.analysis.programs) — one definition
        accum = make_fused_eval_accum(
            self.model, self.seqn, rasterize=self._rasterize,
            compute_dtype=self._compute_dtype,
        )

        repl = NamedSharding(self.mesh, P())
        data = NamedSharding(self.mesh, P("data"))
        mega = NamedSharding(self.mesh, P(None, "data"))
        self._eval_chunk = checked_jit(
            make_multi_step(accum, self.valid_chunk),
            name="eval_chunk",
            in_shardings=((repl, repl), mega),
            out_shardings=repl,
        )
        self._eval_accum = checked_jit(
            lambda carry, batch: accum(carry, batch)[0],
            name="eval_accum",
            in_shardings=((repl, repl), data),
            out_shardings=repl,
        )

    def _fused_readback(self, sums) -> Dict[str, float]:
        """THE one device->host sync of a fused validation pass (counted by
        the one-readback acceptance test; everything before it only
        dispatches)."""
        # host-sync audit: one jax.device_get of three scalars per
        # validation PASS — the readback the fusion exists to amortize
        host = jax.device_get(sums)
        return {k: float(v) for k, v in host.items()}

    def _valid_fused(self, stamp: int) -> Dict[str, float]:
        """Scan-fused validation: ``chunk_windows`` eval batches per
        dispatch, metric sums riding the scan carry, ONE readback per pass.

        Batches are grouped host-side exactly like the train loop's
        megabatches (``collate_megabatch``/``stage_megabatch``); a shape
        change mid-stream (ragged final batch with ``drop_last: false``, a
        resolution change across recordings) flushes the open group through
        the single-batch tail program so the scanned program's shapes stay
        static."""
        from esr_tpu.data.loader import collate_megabatch
        from esr_tpu.parallel.mesh import stage_megabatch

        if self._eval_chunk is None:
            self._build_fused_eval()
        self.valid_metrics.reset()
        t0 = time.monotonic()
        zero = jnp.zeros((), jnp.float32)
        carry = (
            self.state.params,
            {"valid_loss": zero, "valid_mse_loss": zero, "count": zero},
        )
        n_batches = 0
        n_dispatches = 0
        buf = []

        def flush(group):
            nonlocal carry, n_dispatches
            if not group:
                return
            if len(group) == self.valid_chunk:
                mega = stage_megabatch(collate_megabatch(group), self.mesh)
                carry, _ = self._eval_chunk(carry, mega)
                n_dispatches += 1
            else:
                for sel in group:
                    carry = self._eval_accum(
                        carry, stage_batch(sel, self.mesh)
                    )
                    n_dispatches += 1

        for batch in self.valid_loader:
            sel = self._select(batch)
            if buf and any(
                sel[k].shape != buf[0][k].shape for k in sel
            ):
                flush(buf)
                buf = []
            buf.append(sel)
            n_batches += 1
            if len(buf) == self.valid_chunk:
                flush(buf)
                buf = []
        flush(buf)

        sums = self._fused_readback(carry[1])
        self.last_valid_readbacks = 1
        n = int(round(sums["count"]))
        if n:
            # one n-weighted tracker update per key: avg() and the emitted
            # sink record weight exactly like n per-batch updates would
            self.valid_metrics.update(
                "valid_loss", sums["valid_loss"] / n, n=n
            )
            self.valid_metrics.update(
                "valid_mse_loss", sums["valid_mse_loss"] / n, n=n
            )
        if self.sink is not None:
            self.sink.span(
                "validate_fused", time.monotonic() - t0,
                stamp=stamp, batches=n_batches, dispatches=n_dispatches,
                chunk_windows=self.valid_chunk, readbacks=1,
            )
        return self._stamp_valid(stamp)

    def eval_model_performance(self, log: Dict[str, float]):
        """Early-stop / best bookkeeping (reference ``:383-424``)."""
        best = False
        stop_training = False
        if self.mnt_mode != "off":
            if self.mnt_metric not in log:
                logger.warning(
                    "Metric %r not found; ignoring this stamp.", self.mnt_metric
                )
            else:
                value = log[self.mnt_metric]
                improved = (
                    value <= self.mnt_best
                    if self.mnt_mode == "min"
                    else value >= self.mnt_best
                )
                if improved:
                    self.mnt_best = value
                    self.not_improved_count = 0
                    best = True
                else:
                    self.not_improved_count += 1
            if self.not_improved_count > self.early_stop:
                logger.info(
                    "Validation did not improve for %d stamps; stopping.",
                    self.early_stop,
                )
                stop_training = True
        return stop_training, best

    def _dispatch(self, fn, state, batch, err_specs=()):
        """Bounded-retry step dispatch (docs/RESILIENCE.md): a transiently
        failing dispatch (an injected ``dispatch_error``, a preempted-core
        ``XlaRuntimeError``) retries up to ``trainer.dispatch_retries``
        with the SAME staged batch — a dispatch-time failure precedes the
        donated-buffer consumption, so a successful retry is
        trajectory-identical; a mid-execution failure that already donated
        surfaces as an error on the retry instead of being masked."""
        if not err_specs and self.dispatch_retries == 0:
            return fn(state, batch)
        from esr_tpu.resilience.faults import InjectedFault
        from esr_tpu.resilience.recovery import retry_with_backoff

        err = list(err_specs)

        def attempt():
            if err:
                raise InjectedFault(err.pop(0))
            return fn(state, batch)

        return retry_with_backoff(
            attempt, retries=self.dispatch_retries, backoff_s=0.05,
            site="train_step", event="recovery_dispatch_retry",
        )

    def _perform_rollback(self, rb: RollbackSignal) -> int:
        """Restore the last VALID committed checkpoint (or the run-start
        state) after the anomaly guard exhausted its bad-step budget.
        Returns the iteration to resume from; the caller fast-forwards the
        data stream there. ``trainer.max_rollbacks`` bounds the loop — a
        deterministically diverging run must fail loudly, not oscillate
        between rollback and the same NaN forever."""
        from esr_tpu.resilience.recovery import (
            emit_recovery,
            restore_with_fallback,
        )

        if self._guard.rollbacks > self.max_rollbacks:
            raise RuntimeError(
                f"anomaly guard rolled back {self._guard.rollbacks} times "
                f"(budget {self.max_rollbacks}); training diverges "
                "deterministically — refusing to loop"
            ) from rb
        if self._async_ckpt is not None:
            # barrier (never raise: a failed commit means we fall back to
            # an older one, which is exactly what the restore below does)
            self._async_ckpt.wait(raise_error=False)
        state_host, start_iter, best, path = restore_with_fallback(
            self.run.save_dir, self.state, self.run.config
        )
        if path is None:
            if self._init_state is None:
                raise RuntimeError(
                    "rollback requested but no committed checkpoint and "
                    "no run-start snapshot exists"
                ) from rb
            state_host = self._init_state
            start_iter, best = self.start_iteration, None
        self.state = replicate(state_host, self.mesh)
        if best is not None:
            self.mnt_best = best
        self.not_improved_count = 0
        self._guard.consecutive_bad = 0
        emit_recovery(
            "recovery_rollback", site="train_step", fault_id=rb.fault_id,
            from_iteration=rb.at_iteration, to_iteration=start_iter,
            bad_steps=rb.bad_steps, checkpoint=path,
            bad_tag=getattr(rb, "bad_tag", None),
        )
        logger.warning(
            "rolled back to iteration %d (checkpoint %s) after %d "
            "consecutive bad super-steps (first offending tag: %s); "
            "replaying deterministically",
            start_iter, path, rb.bad_steps, getattr(rb, "bad_tag", None),
        )
        return start_iter

    def _save(self, iteration: int, best: bool) -> None:
        # EVERY process participates: Orbax saves are collective under
        # jax.distributed (save_checkpoint writes meta/arrays from the
        # primary host only; the async path preserves this — every
        # process's writer thread runs the same collective commit).
        if self._async_ckpt is not None:
            # blocking cost = barrier(previous commit) + device->host
            # snapshot; the arrays-then-meta.yml commit overlaps the next
            # super-steps on the writer thread (training/async_checkpoint)
            snap_s = self._async_ckpt.save(
                self.run.save_dir,
                self.state,
                self.run.config,
                iteration,
                self.mnt_best,
                save_best=best,
            )
            if self.sink is not None:
                self.sink.span(
                    "checkpoint_snapshot", snap_s,
                    iteration=int(iteration), best=bool(best),
                )
            self._release_init_snapshot()
            return
        save_checkpoint(
            self.run.save_dir,
            self.state,
            self.run.config,
            iteration,
            self.mnt_best,
            save_best=best,
        )
        self._release_init_snapshot()

    def _release_init_snapshot(self) -> None:
        """Free the rollback-of-last-resort run-start state once a
        COMMITTED checkpoint exists on disk (sync save returned, or an
        async commit fully landed) — holding a duplicate host TrainState
        for the whole run would be pure dead weight after that."""
        if self._init_state is None:
            return
        if self._async_ckpt is None or self._async_ckpt.commits > 0:
            self._init_state = None

    # -- the loop ----------------------------------------------------------

    def train(self) -> Dict[str, float]:
        """Run to ``iterations`` (or early stop). Returns final train log."""
        if self.start_iteration >= self.iterations:
            # Resuming an already-finished run (e.g. a `-r auto` requeue
            # loop relaunching after completion) must be a no-op: training
            # one extra step here would persist via the final-state save
            # and compound one iteration per restart.
            logger.info(
                "Run already complete (resumed at iteration %d of %d); "
                "nothing to train.",
                self.start_iteration, self.iterations,
            )
            if self.sink is not None:
                self.sink.close()  # never activated; just release the file
            return {}
        epoch = 0
        iter_idx = self.start_iteration
        valid_stamp = 1
        stop = False
        profiling = False
        self.train_metrics.reset()

        prof = self.profile_cfg
        if prof.get("enabled", False) and self.is_main:
            jax.profiler.start_trace(
                prof.get("trace_dir", self.run.log_dir + "/profile")
            )
            profiling = True

        logger.info(
            "Training: %d iterations, %d sequences/epoch/host, mesh=%s",
            self.iterations,
            len(self.train_loader),
            tuple(self.mesh.shape.items()),
        )

        # Bounded metrics lookahead, mirroring _valid: float(metrics[...])
        # right after dispatch forces a host round-trip every iteration,
        # serializing host batch-building against device compute (the r4
        # bench measured e2e at a small fraction of device-resident
        # steps/s for exactly this reason). Defer the host reads by up to
        # ``train_lookahead`` steps so the loader builds batch N+1 while
        # the device runs step N; drain before anything that needs this
        # iteration's scalars (valid stamps, early stop) or a quiesced
        # state (checkpoint save). Metric VALUES and their step labels
        # are unchanged — only when the host reads them moves.
        from collections import deque

        pending: deque = deque()
        last_scalars = {"loss": float("nan"), "mse": float("nan")}

        if self.numerics:
            from esr_tpu.obs.numerics import (
                merge_readback,
                order_tags,
                poison_tag,
                stats_fields,
            )

        def consume(entry):
            first, r, ep, metrics, vis_batch, bucket, nan_specs = entry
            num_host = None
            # One host readback per SUPER-step (scalars only): the fused
            # path hands back {loss [r], loss_per_window [r, Wc], ...} in
            # a single small transfer; the single-step path (k_steps=1 or
            # the epoch-tail remainder) a list of r per-step dicts. This
            # block is THE cadence-gated sync the attribution resolves
            # against: its duration is the metric_readback span and its end
            # stamps the non-blocking device_step span — no new host syncs.
            with self._attr.resolving(bucket):
                if isinstance(metrics, list):
                    losses = [float(m["loss"]) for m in metrics]
                    mses = [float(m["loss_per_window"][-1]) for m in metrics]
                    last_pred_dev = metrics[-1]["last_pred"]
                    if self.numerics:
                        # part of the SAME cadence-gated readback — tiny
                        # [NSTATS] vectors per tag, no extra sync point
                        num_host = merge_readback(
                            [m["numerics"] for m in metrics]
                        )
                else:
                    losses = [float(v) for v in np.asarray(metrics["loss"])]
                    mses = [
                        float(v)
                        for v in np.asarray(metrics["loss_per_window"])[:, -1]
                    ]
                    last_pred_dev = metrics["last_pred"]
                    if self.numerics:
                        num_host = merge_readback(metrics["numerics"])
            if nan_specs:
                # injected train_step/nan_loss fault: the super-step's
                # readback scalars go non-finite (params untouched — the
                # stand-in for a transient bad loss-scale/reduction, the
                # skippable anomaly class); the guard below must catch it
                losses = [float("nan")] * len(losses)
                mses = [float("nan")] * len(mses)
                if num_host is not None:
                    # the numerics view of the injected fault: the loss
                    # tap is marked non-finite where the scalars were
                    # poisoned, so the layer-named rollback path works
                    # for simulated anomalies exactly like real ones
                    num_host = poison_tag(num_host, "loss")
            if self._guard is not None and not self._guard.check(
                losses, first,
                fault_id=nan_specs[0].fault_id if nan_specs else None,
                numerics=num_host,
            ):
                # skip-and-log (docs/RESILIENCE.md): a non-finite
                # super-step is excluded from trackers/writer/vis so one
                # anomaly cannot poison the run's metric series; the guard
                # already emitted recovery_skip_step (or raised
                # RollbackSignal, unwinding to the rollback handler)
                return
            for j in range(r):
                k = first + j
                loss, mse_loss = losses[j], mses[j]
                if self.writer is not None:
                    self.writer.set_step(k)
                self.train_metrics.update("train_mse_loss", mse_loss)
                self.train_metrics.update("train_loss", loss)
                # lr behind the log cadence (host-sync audit, analysis
                # ESR002 discipline): _schedule_value evaluates an optax
                # jnp expression on host CPU every call — cheap, but it
                # ran EVERY iteration for a scalar nobody reads between
                # log points. train_log_step'd like the loss line.
                if self.writer is not None and k % self.train_log_step == 0:
                    lr = self._schedule_value(k)
                    self.writer.add_scalar("learning_rate", lr)
                    logger.info(
                        "Train Epoch: %d Iteration: %d/%d "
                        "train_mse_loss: %.4e train_loss: %.4e lr: %.4e",
                        ep + 1,
                        k,
                        self.iterations,
                        mse_loss,
                        loss,
                        lr,
                    )
            if (
                self.sink is not None
                and num_host is not None
                and any(k % self.train_log_step == 0 for k in
                        range(first, first + r))
            ):
                # one `numerics` record per probe tag, behind the SAME
                # train_log_step cadence as the loss line — the values
                # were already read back above; this is pure host I/O
                for tag in order_tags(num_host):
                    self.sink.numerics(
                        tag, stats_fields(num_host[tag]),
                        step=first + r - 1,
                    )
            if self.writer is not None and vis_batch is not None:
                # host-sync audit: a device->host transfer of one
                # predicted frame, already behind the vis cadence
                # (keep_vis gates every train_vis_step'th iteration,
                # after the lookahead drain) — never per-step. Under
                # k-step fusion the frame is the super-step's FINAL
                # prediction (vis cadence snaps to super-step boundaries).
                pred = np.asarray(jax.device_get(last_pred_dev)[0])
                self._log_images(vis_batch, pred)
            last_scalars["loss"] = losses[-1]
            last_scalars["mse"] = mses[-1]

        def drain():
            while pending:
                consume(pending.popleft())

        import contextlib

        from esr_tpu.data.loader import DevicePrefetcher, group_batches
        from esr_tpu.obs import trace

        # checkpoint work (snapshot + its background commit) adopts the
        # open super-step bucket's context so those spans parent under
        # the super_step span, not the run root (docs/OBSERVABILITY.md)
        _bucket_ctx = self._attr.current_ctx

        _END = object()  # sentinel: (group, None) is a real inline item

        completed = False
        run_span = None
        live_watermark = None
        profiler = None
        try:
            if self.sink is not None:
                from esr_tpu.obs import set_active_sink

                # process-wide activation for the components with no
                # Trainer reference (retrace guard, prefetcher) — INSIDE
                # the try so the finally's deactivation is unconditional:
                # nothing may raise between install and uninstall
                set_active_sink(self.sink)
                # stamp the cache state next to the compile events it
                # explains: on a warm cache the same `compile` records
                # show near-zero XLA cost (the trace still runs; the
                # lowering is served from disk)
                self.sink.event(
                    "compile_cache",
                    enabled=self.compile_cache_dir is not None,
                    dir=self.compile_cache_dir,
                )
                # run-level trace root (schema v2): every super-step
                # bucket opened below becomes a child of this span, so a
                # whole training run exports as ONE connected trace.
                # Manual begin() because the span brackets the loop, not
                # a lexical block; the matching end() sits in the finally
                # (exactly the contract analysis rule ESR010 enforces).
                run_span = trace.begin(
                    "train_run", sink=self.sink,
                    iterations=self.iterations,
                    start_iteration=self.start_iteration,
                    k_steps=self.k_steps,
                )
                if self.live_cfg is not None:
                    # the opt-in live plane (obs v3): aggregator tapped
                    # into this run's sink + the /metrics-/healthz-/slo
                    # HTTP thread, plus the device-memory watermark
                    # poller (gauges flow through the same tap). The
                    # bound port is stamped as a live_telemetry event so
                    # pollers discover ephemeral (port 0) bindings from
                    # the stream itself.
                    from esr_tpu.obs.device import DeviceWatermark
                    from esr_tpu.obs.http import start_live_plane

                    self.live_plane = start_live_plane(
                        self.sink,
                        port=self.live_cfg["port"],
                        slo_path=self.live_cfg["slo"],
                        windows=self.live_cfg["windows"],
                        rel_err=self.live_cfg["rel_err"],
                    )
                    self.sink.event(
                        "live_telemetry", port=self.live_plane.port,
                        slo=self.live_cfg["slo"],
                    )
                    live_watermark = DeviceWatermark(
                        sink=self.sink,
                        interval_s=self.live_cfg["watermark_interval_s"],
                    ).start()
            if self.profile_steps and self.is_main:
                from esr_tpu.obs.device import ProfilerCapture

                profiler = ProfilerCapture(
                    self.profile_cfg.get(
                        "trace_dir", self.run.log_dir + "/profile"
                    ),
                    self.profile_steps,
                    sink=self.sink,
                    site="train",
                )
                profiler.maybe_start()
            # rollback bookkeeping (docs/RESILIENCE.md): which iteration
            # each epoch started at, so a rollback can re-enter the RIGHT
            # epoch and fast-forward its (seed, epoch)-deterministic batch
            # stream to the checkpoint boundary — the replay consumes the
            # identical batch sequence a fault-free run would have
            epoch_starts: list = []
            ff_skip = 0
            while not stop:
                self.train_loader.set_epoch(epoch)
                if not epoch_starts or epoch_starts[-1][0] != epoch:
                    epoch_starts.append((epoch, iter_idx))
                rb_caught = None
                # host->device upload pipelined ahead of the consuming step;
                # the ExitStack guarantees the producer thread stops even when
                # the loop breaks mid-epoch (early stop, final iteration).
                # The source yields GROUPS of k_steps batches (k_steps=1:
                # singleton groups — today's per-step pipeline exactly); a full
                # group stages as one (k, B, L, ...) megabatch ahead of the
                # consuming fused super-step. The inline (device_prefetch=0)
                # path yields (group, None) and stages in the loop body so the
                # stage_megabatch span is measured on the consumer thread.
                with contextlib.ExitStack() as stack:
                    source = group_batches(self.train_loader, self.k_steps)
                    if ff_skip:
                        source = _fast_forward_groups(source, ff_skip)
                        ff_skip = 0
                    if self.device_prefetch:
                        batches = stack.enter_context(DevicePrefetcher(
                            source,
                            self._stage_group_timed,
                            depth=self.device_prefetch,
                            join_timeout=self.prefetch_join_timeout,
                            stall_timeout=self.prefetch_stall_timeout,
                        ))
                    else:
                        batches = ((g, None) for g in source)
                    it = iter(batches)
                    while True:
                        # one attribution bucket per super-step, opened before
                        # the pull so the blocked wait is its data_wait span
                        self._attr.begin()
                        with self._attr.measure("data_wait"):
                            item = next(it, _END)
                        if item is _END:
                            self._attr.discard()
                            break
                        group, staged = item
                        try:
                            if staged is None:
                                with self._attr.measure("stage_megabatch"):
                                    staged = self._stage_group(group)
                            else:
                                # staged on the prefetcher's producer thread —
                                # overlapped with earlier device compute, so it
                                # reports but is excluded from the wall identity
                                self._attr.add(
                                    "stage_megabatch",
                                    self._stage_spans.pop(id(group), 0.0),
                                    overlapped=True,
                                )
                            best = False
                            r = len(group)
                            # train_step fault site (docs/RESILIENCE.md),
                            # keyed by the super-step's first iteration:
                            # nan_loss poisons THIS super-step's readback
                            # (enacted in consume, where the scalars land);
                            # dispatch_error raises at dispatch and is
                            # absorbed by the bounded retry below
                            _specs = _faults.fire("train_step", iter_idx)
                            nan_specs = [
                                s for s in _specs if s.kind == "nan_loss"
                            ]
                            err_specs = [
                                s for s in _specs
                                if s.kind == "dispatch_error"
                            ]
                            if isinstance(staged, list):
                                # k_steps=1, or the epoch-tail remainder
                                # (< k_steps batches): r sequential single-step
                                # calls — static shapes, no extra compile of
                                # the scanned program
                                metrics = []
                                for sb in staged:
                                    self.state, m = self._dispatch(
                                        self.train_step, self.state, sb,
                                        err_specs,
                                    )
                                    err_specs = []
                                    metrics.append(m)
                            else:
                                # ONE dispatch for k_steps chained train steps
                                self.state, metrics = self._dispatch(
                                    self.multi_step, self.state, staged,
                                    err_specs,
                                )
                            first = iter_idx
                            last = iter_idx + r - 1
                            covered = range(first, last + 1)
                            # advance NOW (nothing below reads the old
                            # value): the early-stop/final-iteration breaks
                            # skip the loop tail, and train_end must report
                            # the true trained count, matching checkpoints
                            iter_idx = last + 1
                            self._attr.note(first, r)
                            if profiler is not None:
                                # one profiled unit per trained iteration;
                                # the capture stops itself (stamping
                                # profiler_capture) at the budget
                                profiler.step(r)
                            # cadences snap to super-step boundaries: due when
                            # ANY covered iteration hits the configured multiple
                            keep_vis = (
                                self.writer is not None
                                and self.vis_enabled
                                and any(
                                    i % self.train_vis_step == 0 for i in covered
                                )
                            )
                            pending.append(
                                (first, r, epoch, metrics,
                                 group[-1] if keep_vis else None,
                                 self._attr.current, nan_specs)
                            )
                            if len(pending) > self.train_lookahead:
                                consume(pending.popleft())

                            valid_due = (
                                self.valid_loader is not None
                                and any(
                                    i % self.valid_step == 0 and i != 0
                                    for i in covered
                                )
                            )
                            save_due = any(
                                i % self.save_period == 0 and i != 0
                                for i in covered
                            )
                            final_due = last + 1 >= self.iterations
                            if valid_due or save_due or final_due:
                                drain()

                            if valid_due:
                                with self._attr.measure("validate"):
                                    val_log = self._valid(valid_stamp)
                                if self.writer is not None:
                                    # stamp-aligned train scalars (reference
                                    # :304-305)
                                    self.writer.add_scalar(
                                        "stamp_train_mse_loss",
                                        last_scalars["mse"],
                                        step=valid_stamp,
                                    )
                                    self.writer.add_scalar(
                                        "stamp_train_loss",
                                        last_scalars["loss"],
                                        step=valid_stamp,
                                    )
                                logger.info(
                                    "Valid stamp %d: %s",
                                    valid_stamp,
                                    {k: round(v, 6) for k, v in val_log.items()},
                                )
                                stop, best = self.eval_model_performance(val_log)
                                valid_stamp += 1
                                if stop:
                                    break

                            saved_now = save_due or best
                            if saved_now:
                                with trace.adopt(_bucket_ctx()), \
                                        self._attr.measure("checkpoint"):
                                    self._save(last, best)

                            if final_due:
                                logger.info("Training completes!")
                                # Final-state checkpoint — deliberate deviation
                                # from the reference, which saves only on
                                # save_period multiples
                                # (train_ours_cnt_seq.py:316-319) and so loses
                                # up to save_period-1 trailing iterations of a
                                # finished run. Under k_steps>1, when
                                # `iterations` is not a super-step multiple the
                                # final fused group trains up to k_steps-1
                                # iterations past it; the checkpoint records
                                # the TRUE last iteration so resume stays
                                # consistent (docs/PERF.md).
                                if not saved_now:
                                    with trace.adopt(_bucket_ctx()), \
                                            self._attr.measure("checkpoint"):
                                        self._save(last, False)
                                stop = True
                                break
                        except RollbackSignal as rb:
                            # the anomaly guard's bad-step budget ran out
                            # (raised at the cadence-gated readback inside
                            # consume/drain): unwind to the epoch level so
                            # the ExitStack stops the prefetcher cleanly,
                            # then restore + fast-forward below
                            rb_caught = rb
                            break
                        finally:
                            # wall-clock end of this super-step's loop body
                            # (idempotent; the bucket lives on in `pending`
                            # until the deferred readback resolves it)
                            self._attr.close()
                if rb_caught is not None:
                    # in-flight readbacks of the poisoned window are
                    # discarded wholesale — everything after the rollback
                    # target is about to be replayed
                    pending.clear()
                    resume_iter = self._perform_rollback(rb_caught)
                    while (len(epoch_starts) > 1
                           and epoch_starts[-1][1] > resume_iter):
                        epoch_starts.pop()
                    epoch, ep_start = epoch_starts[-1]
                    if resume_iter < ep_start:
                        # the rollback target predates this process's data
                        # stream (a resumed run whose newest checkpoint
                        # failed validation): the earlier batches cannot
                        # be replayed — resume at the stream's start with
                        # the restored (older) state, loudly
                        logger.warning(
                            "rollback target iteration %d predates this "
                            "run's data stream (started at %d); replaying "
                            "from the stream start — labels and data "
                            "realign at the next checkpoint",
                            resume_iter, ep_start,
                        )
                        resume_iter = ep_start
                    ff_skip = resume_iter - ep_start
                    iter_idx = resume_iter
                    continue
                epoch += 1
            try:
                drain()
            except RollbackSignal:
                # terminal-drain edge (early stop with a bad step still in
                # flight): there is no loop left to replay into — drop the
                # poisoned readbacks and keep the shutdown path alive
                logger.error(
                    "rollback requested during terminal drain; the final "
                    "in-flight super-steps are excluded from metrics"
                )
                pending.clear()
            if self._async_ckpt is not None:
                # barrier the final commit INSIDE the try: a failed
                # background save must fail the run, not vanish with it
                self._async_ckpt.wait()
            completed = True
        finally:
            # teardown is exception-safe: a crash mid-run must still
            # stop the profiler, close the writer, and deactivate +
            # close the telemetry sink — a leaked active sink would
            # capture every later component in this process into a
            # dead run's telemetry file
            self._stage_spans.clear()
            if self._async_ckpt is not None:
                # exception path: join (and log, never re-raise — the
                # original exception owns the traceback) so no commit
                # outlives the run or writes after the sink closed
                self._async_ckpt.wait(raise_error=False)
            if profiling:
                jax.profiler.stop_trace()
            if profiler is not None:
                # idempotent: a loop shorter than the capture budget
                # still lands the profiler_capture record before the
                # sink closes
                profiler.stop()
            if live_watermark is not None:
                live_watermark.stop()
            if self.live_plane is not None:
                self.live_plane.close()
                self.live_plane = None
            if self.writer is not None:
                self.writer.close()
            if self.sink is not None:
                from esr_tpu.obs import active_sink, set_active_sink

                link = {}
                if run_span is not None:
                    # close the run root FIRST so train_end stays the
                    # stream's terminal record (tail-readers rely on it);
                    # the explicit link keeps the event inside the trace
                    link = {"trace_id": run_span.trace_id,
                            "parent_id": run_span.span_id}
                    run_span.end(completed=completed)
                self.sink.event(
                    "train_end", iterations=iter_idx, epochs=epoch,
                    attribution_records=self._attr.emitted_records,
                    completed=completed, **link,
                )
                if active_sink() is self.sink:
                    set_active_sink(None)
                self.sink.close()
        return self.train_metrics.result()
