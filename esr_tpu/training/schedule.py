"""LR schedules (reference recipe: ExponentialLR with a floor gate).

The reference steps ``ExponentialLR(gamma)`` every ``lr_change_rate``
iterations but only while the current lr is >= ``floor``
(``train_ours_cnt_seq.py:322-325``: the gate reads the lr *before* stepping,
so the final value may land just below the floor and then stays fixed).
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def exponential_with_floor(
    base_lr: float,
    gamma: float = 0.95,
    change_rate: int = 4000,
    floor: float = 1e-4,
):
    """optax-style schedule fn reproducing the reference's gated decay."""
    if base_lr < floor:
        max_decays = 0
    else:
        # decay #m happens iff lr after m-1 decays is still >= floor
        max_decays = math.floor(math.log(floor / base_lr) / math.log(gamma)) + 1
        max_decays = max(max_decays, 0)

    def schedule(step):
        decays = jnp.minimum(step // change_rate, max_decays)
        return base_lr * (gamma ** decays.astype(jnp.float32))

    return schedule
