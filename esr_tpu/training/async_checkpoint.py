"""Async checkpointing: snapshot on the critical path, commit off it.

The sync save (``training/checkpoint.save_checkpoint``) blocks the training
loop for the FULL persistence cost: device→host fetch, Orbax array write,
``wait_until_finished``, ``meta.yml`` commit — a stop-the-world tail the
PR 3 ``checkpoint`` span made visible on every ``save_period`` boundary.
The accelerator never needs to wait on the filesystem; it only needs a
consistent copy of the state before the next donated step deletes it.
This module splits the save accordingly:

- **snapshot** (blocking, small and bounded): device→host copy of the
  state pytree (``checkpoint._to_host`` — the same fetch the sync path
  does first). Must complete before the loop continues, because the next
  train step DONATES the state buffers; a background thread reading them
  later would read freed memory.
- **commit** (background writer thread): the EXISTING atomic protocol —
  Orbax arrays first, ``meta.yml`` last — run by
  ``checkpoint.save_checkpoint`` on the host snapshot. A commit killed
  between the array write and the ``meta.yml`` marker leaves a torn
  directory that ``find_latest_checkpoint`` ignores by construction
  (pinned by ``tests/test_async_checkpoint.py``).

A **barrier** (:meth:`AsyncCheckpointer.wait`) joins the in-flight commit
and re-raises its error. The Trainer barriers in exactly three places:
before every new snapshot (:meth:`save` calls it first — at most ONE save
in flight, so host memory holds at most one extra state copy), before the
final-state save, and in ``train()``'s ``finally`` (so no commit outlives
the run or its telemetry sink).

Multi-process semantics are preserved: Orbax saves are COLLECTIVE under
``jax.distributed`` — every process calls :meth:`save`, every process's
writer thread runs the same commit (Orbax's internal barriers then
rendezvous across the background threads; array/meta data is written by
the primary host only, exactly as in the sync path). The commit ORDER is
identical on every host because the save cadence is config-derived.

Telemetry (docs/OBSERVABILITY.md): the Trainer emits the blocking
``checkpoint_snapshot`` span; the writer thread emits ``checkpoint_commit``
through the process-active sink (thread-safe, never-raising by contract).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional

from esr_tpu.training.checkpoint import _to_host, save_checkpoint

logger = logging.getLogger(__name__)


class AsyncCheckpointError(RuntimeError):
    """A background commit failed; raised at the NEXT barrier so the
    training loop (not a daemon thread) owns the failure."""


class AsyncCheckpointer:
    """One background checkpoint writer with a single-slot pipeline.

    ``save()`` = barrier(previous) + blocking snapshot + enqueue commit.
    ``wait()`` = join the in-flight commit, re-raising its error.
    At most one commit is ever in flight; the snapshot of save N+1 cannot
    start until commit N finished (the double-writer exclusion the torn-
    checkpoint tests pin — two commits racing into one directory is the
    corruption mode this class exists to exclude).
    """

    def __init__(self, commit_retries: int = 2,
                 commit_backoff_s: float = 0.1):
        if commit_retries < 0:
            raise ValueError(
                f"commit_retries must be >= 0, got {commit_retries}"
            )
        if commit_backoff_s <= 0:
            raise ValueError(
                f"commit_backoff_s must be > 0, got {commit_backoff_s}"
            )
        # bounded exponential-backoff retry around each commit attempt
        # (docs/RESILIENCE.md): a transiently failing filesystem (or an
        # injected ckpt_commit fault) re-runs the SAME atomic
        # arrays-then-meta protocol — force=True overwrites the torn
        # directory the failed attempt left, and find_latest_checkpoint
        # never saw it (no meta.yml marker). Retries exhausted -> the
        # error surfaces at the next barrier exactly as before.
        self.commit_retries = int(commit_retries)
        self.commit_backoff_s = float(commit_backoff_s)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self.last_commit_s: Optional[float] = None
        self.commits = 0

    # -- lifecycle ---------------------------------------------------------

    @property
    def in_flight(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def save(
        self,
        ckpt_dir: str,
        state: Any,
        config: Dict,
        iteration: int,
        monitor_best: float,
        save_best: bool = False,
    ) -> float:
        """Barrier + snapshot + background commit.

        Returns the seconds the call BLOCKED (barrier join + device→host
        snapshot + thread start) — the only cost left on the super-step
        critical path; the caller reports it as the ``checkpoint_snapshot``
        span. Raises :class:`AsyncCheckpointError` if the PREVIOUS commit
        failed (the barrier surfaces it before new work is queued).
        """
        t0 = time.monotonic()
        self.wait()
        # device->host fetch BEFORE the loop continues: the next train step
        # donates these buffers, so the copy must be complete (numpy owns
        # its memory) by the time save() returns
        host_state = _to_host(state)
        # capture the submitter's AMBIENT trace context (obs/trace.py) so
        # the writer thread's checkpoint_commit span stays in the causal
        # tree — the Trainer adopts the snapshotting super-step's bucket
        # context around _save, so in production this IS that super-step
        from esr_tpu.obs import trace

        ctx = trace.capture()
        self._thread = threading.Thread(
            target=self._commit,
            args=(ckpt_dir, host_state, config, int(iteration),
                  float(monitor_best), bool(save_best), ctx),
            name="ckpt-commit",
            # daemonic: a crash elsewhere must not hang the process on a
            # disk write; an interrupted commit leaves a torn (meta-less)
            # directory that find_latest_checkpoint ignores
            daemon=True,
        )
        self._thread.start()
        return time.monotonic() - t0

    def wait(self, raise_error: bool = True, timeout: Optional[float] = None):
        """Join the in-flight commit (no-op when idle).

        With ``raise_error`` the commit's exception re-raises here as
        :class:`AsyncCheckpointError`; otherwise it is logged and cleared
        (the ``finally``-path mode — a teardown barrier must not mask the
        original exception).
        """
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():  # timed out; keep the handle for a later wait
                return
            self._thread = None
        # _error is written by the writer thread strictly BEFORE it exits
        # and read here strictly AFTER join() observed it dead — the join
        # is the happens-before edge (single-slot pipeline invariant)
        err, self._error = self._error, None  # esr: noqa(CX001)
        if err is None:
            return
        if raise_error:
            raise AsyncCheckpointError(
                f"background checkpoint commit failed: {err!r}"
            ) from err
        logger.error("background checkpoint commit failed: %r", err)

    # -- the writer thread -------------------------------------------------

    def _commit(self, ckpt_dir, host_state, config, iteration,
                monitor_best, save_best, trace_ctx=None):
        from esr_tpu.obs import trace

        with trace.adopt(trace_ctx):
            self._commit_inner(ckpt_dir, host_state, config, iteration,
                               monitor_best, save_best)

    def _commit_inner(self, ckpt_dir, host_state, config, iteration,
                      monitor_best, save_best):
        import jax

        from esr_tpu.resilience.recovery import retry_with_backoff

        # single-process only: the Orbax save is COLLECTIVE under
        # jax.distributed (every process must call it exactly once per
        # commit — internal sync_global_devices barriers), so one process
        # retrying alone would desynchronize the barrier count and hang
        # the fleet. Multi-process commits keep the fail-at-barrier path;
        # a coordinated retry protocol is future elastic work.
        retries = (
            self.commit_retries if jax.process_count() == 1 else 0
        )
        t0 = time.monotonic()
        try:
            path = retry_with_backoff(
                lambda: save_checkpoint(
                    ckpt_dir, host_state, config, iteration, monitor_best,
                    save_best=save_best,
                ),
                retries=retries,
                backoff_s=self.commit_backoff_s,
                site="ckpt_commit",
                event="recovery_ckpt_retry",
                iteration=iteration,
            )
        except BaseException as e:  # noqa: BLE001 - surfaced at the barrier
            # single-slot invariant: written strictly before this thread
            # exits, read by wait() strictly after join() — the join is
            # the happens-before edge (same invariant as the reader side)
            self._error = e  # esr: noqa(CX001)
            return
        seconds = time.monotonic() - t0
        self.last_commit_s = seconds
        self.commits += 1
        try:
            from esr_tpu.obs import active_sink

            sink = active_sink()
            if sink is not None:
                sink.span(
                    "checkpoint_commit", seconds,
                    iteration=iteration, best=save_best, path=path,
                )
        except Exception:  # noqa: BLE001 - telemetry never fails a commit
            pass
