"""Optimizer construction (registry-based, mirrors the reference recipe).

The reference uses ``torch.optim.Adam(lr=1e-3, weight_decay=1e-4,
amsgrad=True)`` (``config/train_ours_enfssyn.yml:28-34``). torch's Adam
weight decay is L2-added-to-gradient (not decoupled AdamW), so the optax
equivalent is ``add_decayed_weights`` *before* the Adam transform.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from esr_tpu.training.schedule import exponential_with_floor


class _AmsgradState(NamedTuple):
    count: jax.Array
    mu: optax.Updates
    nu: optax.Updates
    nu_max: optax.Updates


def scale_by_amsgrad_torch(
    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8
) -> optax.GradientTransformation:
    """torch-exact AMSGrad: the running max is taken over the *uncorrected*
    second moment (``torch.optim.Adam`` with ``amsgrad=True``), whereas
    ``optax.scale_by_amsgrad`` maxes the bias-corrected one — a small but
    compounding divergence.
    """

    def init(params):
        z = lambda: jax.tree.map(jnp.zeros_like, params)
        return _AmsgradState(jnp.zeros((), jnp.int32), z(), z(), z())

    def update(updates, state, params=None):
        count = state.count + 1
        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, updates)
        nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * g * g, state.nu, updates)
        nu_max = jax.tree.map(jnp.maximum, state.nu_max, nu)
        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** count.astype(jnp.float32)
        out = jax.tree.map(
            lambda m, vm: (m / bc1) / (jnp.sqrt(vm) / jnp.sqrt(bc2) + eps),
            mu,
            nu_max,
        )
        return out, _AmsgradState(count, mu, nu, nu_max)

    return optax.GradientTransformation(init, update)


def make_optimizer(
    name: str = "Adam",
    lr: Union[float, Callable] = 1e-3,
    weight_decay: float = 0.0,
    amsgrad: bool = True,
    betas=(0.9, 0.999),
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    if name not in ("Adam", "AdamW", "SGD"):
        raise KeyError(f"unknown optimizer '{name}'")
    if name == "SGD":
        # torch SGD applies weight decay as L2-on-gradient too.
        parts = []
        if weight_decay:
            parts.append(optax.add_decayed_weights(weight_decay))
        parts.append(optax.sgd(lr))
        return optax.chain(*parts)
    parts = []
    if name == "Adam" and weight_decay:
        # torch Adam: grad += wd * param, then moments.
        parts.append(optax.add_decayed_weights(weight_decay))
    if amsgrad:
        parts.append(scale_by_amsgrad_torch(b1=betas[0], b2=betas[1], eps=eps))
    else:
        parts.append(optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps))
    if name == "AdamW" and weight_decay:
        # decoupled: decay applied after moment normalization.
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(
        optax.scale_by_learning_rate(lr)
    )
    return optax.chain(*parts)


def make_reference_optimizer(iteration_schedule: bool = True):
    """The exact headline training recipe from the reference config."""
    sched = exponential_with_floor(1e-3, gamma=0.95, change_rate=4000, floor=1e-4)
    return make_optimizer("Adam", lr=sched, weight_decay=1e-4, amsgrad=True)
