"""K-step fused training: chain k train steps inside ONE executable.

The r4 bench arbitration (BASELINE.md) pinned device compute at ~57.7
ms/step (b2, f32) against a ~76.8 ms per-call floor: per-step Python
dispatch and host->device re-staging — not the TPU — bound the production
training loop. The bench proved the fix by timing K steps chained inside a
single ``lax.scan`` (the "scan-slope" method); this module promotes that
method from measurement trick to the shipped training path.

``make_multi_step(train_step, k)`` wraps any ``(state, batch) -> (state,
metrics)`` train step into a super-step ``(state, megabatch) -> (state,
metrics)`` where:

- the **megabatch** is the k per-step batches stacked on a new leading
  axis (``{key: (k, B, L, ...)}``, assembled host-side by
  :func:`esr_tpu.data.loader.collate_megabatch` and staged once, ahead of
  the consuming super-step, by the ``DevicePrefetcher``);
- ``lax.scan`` carries the full training state (params / optimizer /
  recurrent ``batch_stats``) through the k chained steps and
  dynamic-slices each step's batch out of the megabatch **on device** —
  one dispatch, one readback per k steps;
- metrics come back with a leading ``k`` axis (``loss [k]``,
  ``loss_per_window [k, Wc]``, ``grad_norm [k]``) so the host still sees
  every per-step scalar, in one small readback per super-step; the only
  non-scalar metric, ``last_pred``, is returned for the FINAL chained
  step only (it exists for the vis cadence, which is snapped to
  super-step boundaries by the Trainer).

``reuse_batch=True`` is the bench-chaining mode: the SAME batch (no k
axis) feeds every chained step. This is exactly what ``bench.py``'s
scan-slope stages time — with the rewire in this module's PR, the
headline benchmark and the production training path share this one
implementation, so the measured number is the shipped code path.

The step being fused does not have to be a TRAIN step: the streaming
inference engine (``esr_tpu.inference.engine``) fuses ``chunk_windows``
per-window eval steps the same way — its carry is ``(recurrent states,
per-lane metric sums)`` and its "megabatch" a window chunk — so train-time
and inference-time fusion share this one scan contract (and its leading-
axis validation).

jit/donation/sharding live one level up
(:func:`esr_tpu.parallel.mesh.make_parallel_multi_step`): the scan carry
is the donated argument, so params/opt state keep single-copy HBM
residency exactly as in the k=1 path.
"""

from __future__ import annotations

from typing import Callable

import jax

from esr_tpu.obs import trace


def make_multi_step(
    train_step: Callable, k: int, *, reuse_batch: bool = False
) -> Callable:
    """Fuse ``k`` applications of ``train_step`` into one scanned callable.

    Args:
      train_step: ``(state, batch) -> (state, metrics)``; any pytree state
        and dict-of-arrays metrics (e.g. the output of
        :func:`esr_tpu.training.train_step.make_train_step`).
      k: number of chained steps per call (static; ``k=1`` is valid and
        traces to a length-1 scan whose per-step numerics are identical to
        one plain ``train_step`` call).
      reuse_batch: when True, ``multi_step(state, batch)`` feeds the SAME
        batch (no leading k axis) to every chained step — the bench
        chaining mode. When False (production), ``multi_step(state,
        megabatch)`` expects every megabatch leaf to carry a leading axis
        of length ``k`` and scans over it.

    Returns ``multi_step(state, megabatch) -> (state, metrics)`` with
    metrics stacked on a leading ``k`` axis (``last_pred``, when present,
    is the final step's only — carrying k full predictions to the host
    would defeat the scalar-only readback this fusion exists for).
    """
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")

    def multi_step(state, megabatch):
        if reuse_batch:

            def body(s, _):
                return train_step(s, megabatch)

            state, metrics = jax.lax.scan(body, state, None, length=k)
        else:
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                megabatch
            )[0]:
                shape = getattr(leaf, "shape", None)
                if shape is None or tuple(shape[:1]) != (k,):
                    raise ValueError(
                        f"megabatch leaf {jax.tree_util.keystr(path)} has "
                        f"shape {shape}; expected leading axis {k} "
                        f"(one slice per chained step)"
                    )
            state, metrics = jax.lax.scan(train_step, state, megabatch)
        if isinstance(metrics, dict) and "last_pred" in metrics:
            metrics = dict(metrics)
            metrics["last_pred"] = metrics["last_pred"][-1]
        return state, metrics

    return multi_step


class _InstrumentedStep:
    """Host-side dispatch instrumentation around a COMPILED (super-)step.

    Wraps the jitted callable one level OUTSIDE the jit boundary: the
    ``dispatch`` span times the call itself (tracing + XLA compilation on a
    (re)trace, microseconds on cache hits) and the post-call timestamp
    opens the non-blocking ``device_step`` span that the Trainer's
    cadence-gated metrics readback later resolves
    (``esr_tpu.obs.spans.StepAttribution``) — telemetry never enters the
    traced program. Attribute access (``retrace_counter``, ``lower``, …)
    delegates to the wrapped step, and with no open attribution bucket the
    wrapper is a plain pass-through, so instrumented steps stay usable
    outside the training loop (tests, bench).
    """

    def __init__(self, step: Callable, attribution):
        self._step = step
        self._attribution = attribution

    def __call__(self, *args, **kwargs):
        attribution = self._attribution
        # run the dispatch under the super-step's trace context (schema
        # v2): a (re)trace firing inside this call emits its `compile`
        # event as a CHILD of the super-step span, so a retrace storm is
        # attributable to the exact super-step that paid for it
        with trace.adopt(attribution.current_ctx()):
            with attribution.measure("dispatch"):
                out = self._step(*args, **kwargs)
            attribution.dispatched()
        return out

    def __getattr__(self, name):
        return getattr(self._step, name)


def instrument_dispatch(step: Callable, attribution) -> Callable:
    """Span hooks around the scanned super-step (and the plain step): wrap
    a compiled ``(state, batch) -> (state, metrics)`` callable so each call
    records its host-side ``dispatch`` span and device-step dispatch
    timestamp into ``attribution``."""
    return _InstrumentedStep(step, attribution)
