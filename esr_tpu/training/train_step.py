"""The jit'd training step: BPTT over overlapping event windows via lax.scan.

Rebuilds the reference's python BPTT loop (``train_ours_cnt_seq.py:206-235``)
the TPU way: the ``(L - seqn + 1)`` overlapping windows of a length-L frame
sequence are scanned with ``jax.lax.scan`` carrying the bidirectional ConvGRU
states, the per-window MSE on the middle frame is accumulated
(``mid_idx = (seqn - 1) // 2``, reference ``:195,217-231``), and ONE gradient
step covers the whole sequence — exactly the reference's loss-sum-then-
backward semantics, but compiled as a single XLA program with no host
round-trips.

Data parallelism: jit with a sharded batch. When the batch is sharded over a
``('data',)`` mesh axis and params are replicated, XLA inserts the gradient
all-reduce automatically (the DDP-allreduce equivalent rides ICI).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

Array = jax.Array


def _split_vars(variables):
    """``model.init`` output -> (trainable params, batch_stats or None).

    Accepts either a full flax variables dict (``{'params': ..., opt.
    'batch_stats': ...}`` — what every caller passes) or a bare param tree.
    """
    if isinstance(variables, dict) and "params" in variables:
        return variables["params"], variables.get("batch_stats", None)
    return variables, None


def _merge_vars(params, stats):
    out = {"params": params}
    if stats is not None:
        out["batch_stats"] = stats
    return out


class TrainState(NamedTuple):
    """Carried training state (variables + optimizer + step counter).

    ``params`` holds the FULL flax variables dict (the ``'params'``
    collection plus, for BN models, ``'batch_stats'`` running averages —
    reference ``nn.BatchNorm2d`` buffers). The optimizer state covers only
    the trainable ``'params'`` subtree.
    """

    params: Any
    opt_state: Any
    step: Array

    @classmethod
    def create(cls, params, optimizer: optax.GradientTransformation):
        return cls(
            params=params,
            opt_state=optimizer.init(_split_vars(params)[0]),
            step=jnp.zeros((), jnp.int32),
        )


def _make_windows(seq: Array, seqn: int) -> Array:
    """``[B, L, ...] -> [Wc, B, seqn, ...]`` overlapping windows, stride 1.

    Mirrors the reference's collate ``cat_tensor_dim0`` windowing
    (``h5dataloader.py:210-233``). NOTE: materializes all Wc overlapping
    copies (~seqn x the sequence) — the train/eval scans below instead
    ``dynamic_slice`` each window out of the sequence inside the scan body,
    which keeps HBM at 1x; this helper remains for host-side/windowing
    tests and small utilities.
    """
    L = seq.shape[1]
    wc = L - seqn + 1
    return jnp.stack([seq[:, i : i + seqn] for i in range(wc)], axis=0)


def _window_slicer(inp: Array, gt: Array, seqn: int, mid_idx: int):
    """Scan-body window access: ``i -> (inp[:, i:i+seqn], gt[:, i+mid])``
    via dynamic_slice — no [Wc, ...] window tensor in HBM."""

    def slice_window(i):
        window = jax.lax.dynamic_slice_in_dim(inp, i, seqn, axis=1)
        gtw = jax.lax.dynamic_index_in_dim(
            gt, i + mid_idx, axis=1, keepdims=False
        )
        return window, gtw

    return slice_window


def make_device_rasterizer(gt_resolution: Tuple[int, int]) -> Callable:
    """Build the on-device rasterization stage for raw-event batches.

    The BASELINE north-star input path: the host ships fixed-capacity padded
    event windows (tiny: ~4 floats/event) and the TPU scatter-adds them into
    count images inside the jit'd step — HBM-resident rasterization instead
    of host rasterization + dense-tensor transfer. Consumes
    ``{"inp_events" [B, L, N, 4] (normalized coords), "inp_valid" [B, L, N],
    "gt_events" [B, L, Ng, 4] (raw GT-grid coords), "gt_valid"}`` and
    produces the ``{"inp", "gt"}`` dense batch the loss expects.

    The encoder itself lives with its jnp twins in ``ops/encodings``
    (:func:`esr_tpu.ops.encodings.make_device_encoder`) so inference and
    serving can stage the same raw-event contract; this name remains the
    training-side seam.
    """
    from esr_tpu.ops.encodings import make_device_encoder

    return make_device_encoder(gt_resolution)


def make_train_step(
    model,
    optimizer: optax.GradientTransformation,
    seqn: int = 3,
    remat: bool = False,
    compute_dtype: Optional[Any] = None,
    rasterize: Optional[Callable] = None,
    numerics: bool = False,
) -> Callable:
    """Build the jit-able train step.

    ``batch`` is a dict with:
      - ``inp``: ``[B, L, H, W, C]`` input frames already rasterized onto the
        HR grid (the ``inp_scaled_cnt`` stream);
      - ``gt``: ``[B, L, H, W, C]`` ground-truth HR frames.

    ``compute_dtype``: standard mixed precision — ``jnp.bfloat16`` runs the
    forward/backward convs at the MXU's native width (params are CAST for the
    apply, master copies and optimizer state stay f32, losses accumulate in
    f32). The reference trains pure f32; bf16 is the TPU-first option.

    ``numerics`` (the numerics plane, docs/OBSERVABILITY.md): read the
    model's sown tensor-stats probes back per window, accumulate them
    across the BPTT window scan IN THE CARRY (running max for extrema,
    sums for counts — ``ops/numerics.py``), and add ``loss`` /
    ``grad_norm`` taps — the whole bundle rides the existing metrics
    readback as ``metrics["numerics"]`` (``{tag: f32[NSTATS]}``), so the
    cadence-gated readback stays the ONLY host sync. Requires a model
    built with ``numerics=True`` (the probes live in the model); with
    ``numerics=False`` (default) this factory's traced program is
    bitwise-identical to a build without the plane (pinned).

    Returns ``(state, metrics) = train_step(state, batch)``.
    """
    mid_idx = (seqn - 1) // 2

    # train=True / mutable are baked in BEFORE jax.checkpoint wraps the
    # callable: checkpoint flattens every argument into tracers, which would
    # turn a passed-through `train` bool into a tracer and break flax's
    # `if train:` branches.
    def _fwd_plain(variables, window, states):
        return model.apply(variables, window, states, train=True)

    def _fwd_bn(variables, window, states):
        return model.apply(
            variables, window, states, train=True, mutable=["batch_stats"]
        )

    # numerics twins: same apply with the 'numerics' sow collection
    # mutable, handing the per-window probe tree back alongside the
    # prediction. Separate defs (not a runtime branch) so the default-off
    # program traces byte-identically.
    def _fwd_plain_num(variables, window, states):
        (pred, states), mut = model.apply(
            variables, window, states, train=True, mutable=["numerics"]
        )
        return pred, states, mut["numerics"]

    def _fwd_bn_num(variables, window, states):
        (pred, states), mut = model.apply(
            variables, window, states, train=True,
            mutable=["batch_stats", "numerics"],
        )
        return pred, states, mut

    if remat:
        _fwd_plain = jax.checkpoint(_fwd_plain)
        _fwd_bn = jax.checkpoint(_fwd_bn)
        _fwd_plain_num = jax.checkpoint(_fwd_plain_num)
        _fwd_bn_num = jax.checkpoint(_fwd_bn_num)

    def loss_fn(param_col, stats, batch):
        if rasterize is not None:
            batch = rasterize(batch)
        inp, gt = batch["inp"], batch["gt"]
        if compute_dtype is not None:
            param_col = jax.tree.map(
                lambda p: p.astype(compute_dtype), param_col
            )
            inp = inp.astype(compute_dtype)
        b, L = inp.shape[0], inp.shape[1]
        # GT for window w is the middle frame of that window; each window is
        # dynamic-sliced inside the scan (no [Wc, ...] HBM tensor).
        slice_window = _window_slicer(inp, gt, seqn, mid_idx)
        idxs = jnp.arange(L - seqn + 1)
        states0 = model.init_states(b, inp.shape[2], inp.shape[3])
        if compute_dtype is not None:
            states0 = jax.tree.map(
                lambda s: s.astype(compute_dtype), states0
            )
        # only the LAST window's prediction is reported — carry it instead
        # of stacking every window's output
        pred0 = jnp.zeros_like(gt[:, 0], dtype=jnp.float32)

        if numerics:
            # probe-tag structure from a device-free shape trace of one
            # window forward, so the scan carry's accumulator pytree is
            # known before the scan body traces
            from esr_tpu.ops.numerics import (
                flatten_probes,
                merge_stat_vectors,
                zero_stats,
            )

            if stats is None:
                probes_shape = jax.eval_shape(
                    _fwd_plain_num, {"params": param_col},
                    inp[:, :seqn], states0,
                )[2]
            else:
                probes_shape = jax.eval_shape(
                    _fwd_bn_num,
                    {"params": param_col, "batch_stats": stats},
                    inp[:, :seqn], states0,
                )[2]["numerics"]
            acc0 = {
                tag: zero_stats() for tag in flatten_probes(probes_shape)
            }

        # `numerics` is a static python bool, so the probe branches below
        # are resolved at trace time: the default-off program is
        # byte-identical to a build without the plane (lowered-text pin
        # in tests/test_obs_numerics.py and the bench numerics_overhead
        # cell). One body per BN variant — the window slice / forward /
        # f32 loss math exists once per path, never per knob.
        if stats is None:

            def body(carry, i):
                if numerics:
                    states, _, acc = carry
                else:
                    states, _ = carry
                window, gtw = slice_window(i)
                if numerics:
                    pred, states, sown = _fwd_plain_num(
                        {"params": param_col}, window, states
                    )
                    stats_i = flatten_probes(sown)
                    acc = {
                        t: merge_stat_vectors(acc[t], stats_i[t])
                        for t in acc
                    }
                else:
                    pred, states = _fwd_plain(
                        {"params": param_col}, window, states
                    )
                predf = pred.astype(jnp.float32)  # loss math in f32
                err = predf - gtw
                carry = (
                    (states, predf, acc) if numerics else (states, predf)
                )
                return carry, (err**2).mean()

            carry0 = (
                (states0, pred0, acc0) if numerics else (states0, pred0)
            )
            out_carry, losses = jax.lax.scan(body, carry0, idxs)
            last_pred = out_carry[1]
            probe_acc = out_carry[2] if numerics else None
            new_stats = None
        else:
            # BN models: running stats update on every window forward (torch
            # updates per forward() call inside the reference's BPTT loop),
            # so the stats ride the scan carry alongside the GRU states.
            def body(carry, i):
                if numerics:
                    states, st, _, acc = carry
                else:
                    states, st, _ = carry
                window, gtw = slice_window(i)
                if numerics:
                    pred, states, mut = _fwd_bn_num(
                        {"params": param_col, "batch_stats": st},
                        window, states,
                    )
                    stats_i = flatten_probes(mut["numerics"])
                    acc = {
                        t: merge_stat_vectors(acc[t], stats_i[t])
                        for t in acc
                    }
                else:
                    (pred, states), mut = _fwd_bn(
                        {"params": param_col, "batch_stats": st},
                        window, states,
                    )
                predf = pred.astype(jnp.float32)
                err = predf - gtw
                carry = (
                    (states, mut["batch_stats"], predf, acc)
                    if numerics else (states, mut["batch_stats"], predf)
                )
                return carry, (err**2).mean()

            carry0 = (
                (states0, stats, pred0, acc0)
                if numerics else (states0, stats, pred0)
            )
            out_carry, losses = jax.lax.scan(body, carry0, idxs)
            new_stats = out_carry[1]
            last_pred = out_carry[2]
            probe_acc = out_carry[3] if numerics else None
        # reference accumulates the SUM of per-window MSEs before backward
        return losses.sum(), (losses, last_pred, new_stats, probe_acc)

    def train_step(state: TrainState, batch) -> Tuple[TrainState, dict]:
        param_col, stats = _split_vars(state.params)
        (loss, (losses, last_pred, new_stats, probe_acc)), grads = (
            jax.value_and_grad(loss_fn, has_aux=True)(
                param_col, stats, batch
            )
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, param_col
        )
        param_col = optax.apply_updates(param_col, updates)
        new_state = TrainState(
            _merge_vars(param_col, new_stats)
            if isinstance(state.params, dict) and "params" in state.params
            else param_col,
            opt_state,
            state.step + 1,
        )
        grad_norm = optax.global_norm(grads)
        metrics = {
            "loss": loss,
            "loss_per_window": losses,
            "grad_norm": grad_norm,
            "last_pred": last_pred,
        }
        if numerics:
            from esr_tpu.ops.numerics import tensor_stats

            # the training-side taps join the model's: the window-summed
            # per-window losses and the global grad norm, in the same
            # stats-vector format so one readback path serves all tags
            metrics["numerics"] = {
                **probe_acc,
                "loss": tensor_stats(losses),
                "grad_norm": tensor_stats(grad_norm),
            }
        return new_state, metrics

    return train_step


def make_eval_step(
    model, seqn: int = 3, rasterize: Optional[Callable] = None,
    compute_dtype: Optional[Any] = None,
) -> Callable:
    """Validation step: same scan, no grad (reference ``_valid``,
    ``train_ours_cnt_seq.py:541-633``).

    ``compute_dtype`` mirrors :func:`make_train_step`: params/inputs/
    states are cast for the apply so a ``trainer.precision: bf16`` run
    validates the program it actually trains, while the per-window MSE is
    reduced from an f32-cast prediction so the monitored scalars keep f32
    accumulation (the drift harness, not the metric sums, judges the
    rung). ``None`` traces the unmodified f32 reference program.
    """
    mid_idx = (seqn - 1) // 2

    def eval_step(params, batch) -> dict:
        if rasterize is not None:
            batch = rasterize(batch)
        inp, gt = batch["inp"], batch["gt"]
        if compute_dtype is not None:
            params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
            inp = inp.astype(compute_dtype)
        b, L = inp.shape[0], inp.shape[1]
        slice_window = _window_slicer(inp, gt, seqn, mid_idx)
        idxs = jnp.arange(L - seqn + 1)
        states0 = model.init_states(b, inp.shape[2], inp.shape[3])
        if compute_dtype is not None:
            states0 = jax.tree.map(
                lambda a: a.astype(compute_dtype), states0
            )

        def body(states, i):
            window, gtw = slice_window(i)
            pred, states = model.apply(params, window, states)
            predf = pred.astype(jnp.float32)
            return states, ((predf - gtw) ** 2).mean()

        _, losses = jax.lax.scan(body, states0, idxs)
        # valid_loss = window-summed MSE, valid_mse_loss = last window's MSE —
        # the reference logs both (train_ours_cnt_seq.py:571-589: `loss`
        # accumulates, `mse_loss` holds the loop's final value).
        return {"valid_loss": losses.sum(), "valid_mse_loss": losses[-1]}

    return eval_step


def make_fused_eval_accum(
    model, seqn: int = 3, rasterize: Optional[Callable] = None,
    compute_dtype: Optional[Any] = None,
) -> Callable:
    """The scanned accumulator behind fused validation: ``((params, sums),
    batch) -> ((params, sums), {})`` where ``sums`` carries the
    globally-reduced ``valid_loss``/``valid_mse_loss``/``count`` scalars
    ON DEVICE across batches — chain it through
    :func:`~esr_tpu.training.multistep.make_multi_step` for the
    one-readback-per-pass validation program (the Trainer's
    ``_build_fused_eval``) and audit it through
    ``esr_tpu.analysis.programs`` (the jaxpr auditor registers exactly
    this composition as the production validation program)."""
    eval_fn = make_eval_step(
        model, seqn, rasterize=rasterize, compute_dtype=compute_dtype
    )

    def accum(carry, batch):
        params, sums = carry
        out = eval_fn(params, batch)
        sums = {
            "valid_loss": sums["valid_loss"] + out["valid_loss"],
            "valid_mse_loss": (
                sums["valid_mse_loss"] + out["valid_mse_loss"]
            ),
            "count": sums["count"] + 1.0,
        }
        return (params, sums), {}

    return accum


def jit_eval_step(
    model,
    seqn: int = 3,
    rasterize: Optional[Callable] = None,
    compute_dtype: Optional[Any] = None,
    max_traces: int = 8,
    **jit_kwargs,
) -> Callable:
    """:func:`make_eval_step` jitted through the retrace guard.

    The validation loader runs every ``valid_step`` iterations for the whole
    training run — a shape leak there (ragged final batch, a resolution
    drifting per recording) recompiles on every stamp and silently doubles
    wall-clock. ``checked_jit`` raises past ``max_traces`` instead.
    ``jit_kwargs`` (``in_shardings``/``out_shardings``/...) pass through.
    """
    from esr_tpu.analysis.retrace_guard import checked_jit

    return checked_jit(
        make_eval_step(
            model, seqn, rasterize=rasterize, compute_dtype=compute_dtype
        ),
        name="eval_step",
        max_traces=max_traces,
        **jit_kwargs,
    )
