"""Checkpoint save/restore: Orbax pytrees + embedded config.

Rebuilds the reference's checkpointing (``train_ours_cnt_seq.py:635-725``,
``myutils/utils.py:140-177``) per SURVEY.md §5 ("Orbax checkpointing with the
same embedded-config convention"):

- a checkpoint is a directory ``checkpoint-iteration{N}/`` holding the
  ``state/`` pytree (params + optimizer state + step) and ``meta.yml`` with
  the FULL effective config plus trainer progress
  ``{training_mode, iteration, monitor_best}`` — self-describing, so
  inference rebuilds the model from the checkpoint alone
  (reference ``infer_ours_cnt.py:123-127``);
- new-best saves ``model_best_until_iteration{N}/`` (reference ``:682-685``);
- resume is name-checked per component (model/optimizer names recorded in
  ``meta.yml`` must match the live config, reference ``Resumer``,
  ``myutils/utils.py:147-171``): a model-name mismatch skips the whole
  restore; an optimizer-name mismatch restores params but re-initializes
  optimizer state;
- ``--reset`` restores weights but zeroes trainer progress
  (reference ``:697-722``).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import yaml

from esr_tpu.training.train_step import TrainState

logger = logging.getLogger(__name__)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


# Bumped when the TrainState pytree layout changes. 2: optimizer state
# covers only the 'params' collection (batch_stats ride outside it);
# format-1 checkpoints had opt_state rooted at the full variables dict.
CHECKPOINT_FORMAT = 2


def save_checkpoint(
    ckpt_dir: str,
    state: TrainState,
    config: Dict,
    iteration: int,
    monitor_best: float,
    training_mode: str = "iteration_based_train",
    save_best: bool = False,
) -> str:
    """Write ``checkpoint-iteration{N}`` (and the best-alias when asked)."""
    from esr_tpu.resilience import faults

    # ckpt_commit fault site (docs/RESILIENCE.md), keyed by iteration:
    # `fail` raises before any byte lands (a commit attempt that never
    # starts — the retry path's clean case); `torn` raises between the
    # Orbax array write and the meta.yml marker (the exact preemption
    # window the commit protocol tolerates). One fire() per commit
    # attempt; a retried commit finds the spec consumed and succeeds.
    _inj = faults.fire("ckpt_commit", iteration)
    for spec in _inj:
        if spec.kind == "fail":
            raise faults.InjectedFault(spec)
    meta = {
        "format": CHECKPOINT_FORMAT,
        "model": {"name": config["model"]["name"]},
        "optimizer": {"name": config["optimizer"]["name"]},
        "lr_scheduler": {
            "name": (config.get("lr_scheduler") or {}).get("name")
        },
        "config": config,
        "trainer": {
            "training_mode": training_mode,
            "iteration": int(iteration),
            "monitor_best": float(monitor_best),
        },
    }
    ckptr = _checkpointer()
    names = [f"checkpoint-iteration{iteration}"]
    if save_best:
        names.append(f"model_best_until_iteration{iteration}")
    paths = [os.path.join(os.path.abspath(ckpt_dir), n) for n in names]
    host_state = _to_host(state)
    # Orbax saves are COLLECTIVE under jax.distributed (internal
    # sync_global_devices barriers): every process must call save(); Orbax
    # itself writes array data from the primary host only. force=True:
    # re-saving an iteration that already has a directory (resume re-runs
    # the iteration that was in flight at preemption; a torn dir without
    # the meta.yml commit marker) must overwrite, not abort.
    for path in paths:
        ckptr.save(os.path.join(path, "state"), host_state, force=True)
    # meta.yml is the COMMIT MARKER: it must only exist once the async Orbax
    # save has landed, so a preemption mid-save leaves a directory that
    # find_latest_checkpoint will ignore rather than a torn checkpoint.
    # Written temp-then-rename: `open(meta.yml, "w")` would CREATE the
    # marker before a single byte of yaml landed, so a writer killed
    # mid-dump (the async commit thread's exact preemption window,
    # tests/test_async_checkpoint.py) would leave a present-but-torn
    # marker; os.replace makes the marker appear atomically, complete.
    ckptr.wait_until_finished()
    for spec in _inj:
        if spec.kind == "torn":
            raise faults.InjectedFault(spec)
    if jax.process_index() == 0:
        from esr_tpu.resilience.recovery import state_digest, write_digest

        # integrity sidecar BEFORE the meta.yml marker: a committed
        # checkpoint always carries the digest of the exact host snapshot
        # its arrays were written from, so restore can prove the artifact
        # unchanged (recovery.validate_restored) before trusting it
        digest = state_digest(host_state)
        for path in paths:
            write_digest(path, digest)
            meta_path = os.path.join(path, "meta.yml")
            tmp_path = meta_path + ".tmp"
            with open(tmp_path, "w") as f:
                yaml.safe_dump(meta, f, sort_keys=False)
            os.replace(tmp_path, meta_path)
            logger.info("Saved checkpoint: %s", path)
    return paths[-1]


def _to_host(tree):
    """Materialize a state pytree on the host.

    Multi-process: DP state is fully replicated, so the process-local shard
    carries the complete value — read shard 0. A genuinely sharded leaf
    would silently save one shard, so refuse it loudly (gather first).
    """

    def get(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            if not x.is_fully_replicated:
                raise ValueError(
                    "checkpointing a non-replicated multi-process array "
                    f"(global shape {x.shape}); all-gather it first"
                )
            return np.asarray(x.addressable_data(0))
        return np.asarray(x)

    return jax.tree.map(get, tree)


def read_meta(path: str) -> Dict:
    with open(os.path.join(path, "meta.yml")) as f:
        return yaml.safe_load(f)


def find_committed_checkpoints(root: str) -> list:
    """Every COMMITTED ``checkpoint-iteration{N}`` under ``root``
    (searched recursively), newest-first by ``meta.yml`` mtime (iteration
    as tie-break) — the candidate list the validated-fallback restore
    (``resilience.recovery.restore_with_fallback``) walks.

    Committed means the ``meta.yml`` marker exists AND parses as the
    expected mapping: a torn save has no marker, and a garbage/truncated
    marker (a writer killed mid-``os.replace`` on exotic filesystems, a
    corrupted disk) is skipped with a loud warning — a broken marker must
    never be silently preferred over an older intact commit."""
    found = []
    for dirpath, dirnames, _ in os.walk(root):
        matched = [d for d in dirnames if d.startswith("checkpoint-iteration")]
        # never descend into checkpoint state trees (deep Orbax array dirs)
        dirnames[:] = [
            d for d in dirnames
            if not d.startswith(("checkpoint-iteration", "model_best_until"))
        ]
        for d in matched:
            try:
                it = int(d[len("checkpoint-iteration"):])
            except ValueError:
                continue
            path = os.path.join(dirpath, d)
            meta = os.path.join(path, "meta.yml")
            if not os.path.exists(meta):
                continue  # uncommitted / torn save
            try:
                with open(meta) as f:
                    doc = yaml.safe_load(f)
                if not isinstance(doc, dict) or "model" not in doc:
                    raise ValueError("not a checkpoint meta mapping")
            except Exception as e:  # noqa: BLE001 - corrupt marker: skip loud
                logger.error(
                    "checkpoint %s has a corrupt meta.yml (%r); treating "
                    "as uncommitted and falling back to an older commit",
                    path, e,
                )
                continue
            found.append(((os.path.getmtime(meta), it), path))
    found.sort(reverse=True)
    return [path for _, path in found]


def find_latest_checkpoint(root: str) -> Optional[str]:
    """Most recently SAVED committed ``checkpoint-iteration{N}`` under
    ``root`` — the preemption-recovery hook: ``train.py -r auto`` resumes
    from whatever the killed run saved last.

    "Latest" is by ``meta.yml`` mtime (iteration as tie-break), NOT by
    iteration number: a ``--reset`` restart in a new run id would otherwise
    be shadowed forever by an abandoned run's higher-iteration checkpoint.
    Only committed checkpoints count — torn saves (no ``meta.yml``) and
    corrupt markers are skipped (:func:`find_committed_checkpoints`).
    Returns None when nothing is found."""
    committed = find_committed_checkpoints(root)
    return committed[0] if committed else None


def restore_state(path: str, template: TrainState) -> TrainState:
    """Restore the raw state pytree into ``template``'s structure."""
    ckptr = _checkpointer()
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        _to_host(template),
    )
    restored = ckptr.restore(os.path.join(os.path.abspath(path), "state"), abstract)
    return jax.tree.map(lambda t, r: np.asarray(r), template, restored)


def resume_checkpoint(
    path: str,
    state: TrainState,
    config: Dict,
    reset: bool = False,
    training_mode: str = "iteration_based_train",
    restored: Optional[TrainState] = None,
) -> Tuple[TrainState, int, Optional[float]]:
    """Name-checked resume. Returns ``(state, start_iteration, monitor_best)``.

    ``monitor_best`` is None when trainer progress was NOT restored (reset,
    training-mode mismatch, model-name mismatch) — the caller keeps its
    freshly initialized monitor sentinel, which depends on the monitor MODE
    (+inf for 'min', -inf for 'max'), so a hard-coded value here would
    corrupt 'max'-mode monitors.

    Mirrors the reference's semantics: same training mode and no ``--reset``
    → trainer progress restored (``start = iteration + 1``); otherwise weights
    only (``train_ours_cnt_seq.py:697-722``).

    ``restored`` (optional) is a state pytree ALREADY restored from
    ``path`` — the validated-fallback path (``resilience.recovery``)
    passes the copy it just integrity-checked so the checkpoint is not
    read from disk a second time.
    """
    meta = read_meta(path)

    fmt = meta.get("format", 1)
    if fmt != CHECKPOINT_FORMAT:
        # Warn-and-start-fresh, like the adjacent model-name-mismatch path:
        # `-r auto` pointed at a directory holding an old-format run should
        # begin training, not abort startup. load_for_inference keeps the
        # hard error — there, silently ignoring the checkpoint would be
        # wrong (ADVICE r3).
        logger.warning(
            "Checkpoint %s has state format %s, this build writes %s "
            "(TrainState pytree layout changed) — not resuming; training "
            "starts fresh.",
            path, fmt, CHECKPOINT_FORMAT,
        )
        return state, 0, None

    if meta["model"]["name"] != config["model"]["name"]:
        logger.warning(
            "Checkpoint model %r != configured %r — not resuming.",
            meta["model"]["name"],
            config["model"]["name"],
        )
        return state, 0, None

    if restored is None:
        restored = restore_state(path, state)

    if meta["optimizer"]["name"] != config["optimizer"]["name"]:
        logger.warning(
            "Checkpoint optimizer %r != configured %r — restoring params only.",
            meta["optimizer"]["name"],
            config["optimizer"]["name"],
        )
        restored = TrainState(
            params=restored.params, opt_state=state.opt_state, step=state.step
        )

    trainer_meta = meta.get("trainer", {})
    same_mode = trainer_meta.get("training_mode") == training_mode
    if reset or not same_mode:
        logger.info("Checkpoint loaded; trainer progress reset.")
        restored = TrainState(
            params=restored.params,
            opt_state=restored.opt_state,
            step=np.zeros((), np.int32),
        )
        return restored, 0, None

    start = int(trainer_meta.get("iteration", 0)) + 1
    best = float(trainer_meta.get("monitor_best", float("inf")))
    logger.info(
        "Checkpoint loaded; resuming from iteration %d (best=%g).", start, best
    )
    return restored, start, best


def load_for_inference(path: str) -> Tuple[Any, Any, Dict]:
    """Rebuild ``(model, params, config)`` from a checkpoint directory alone.

    The reference equivalent builds the model from the config embedded in the
    ``.pth`` (``infer_ours_cnt.py:118-132``). Only ``params`` is materialized;
    the optimizer state in the checkpoint is ignored.
    """
    import jax.numpy as jnp

    from esr_tpu.config.build import build_model, build_optimizer

    meta = read_meta(path)
    fmt = meta.get("format", 1)
    if fmt != CHECKPOINT_FORMAT:
        raise ValueError(
            f"Checkpoint {path} has state format {fmt}, this build reads "
            f"{CHECKPOINT_FORMAT} — see resume_checkpoint."
        )
    config = meta["config"]
    model = build_model(config["model"])

    # Shape-only init to learn the full state structure (conv params are
    # independent of spatial size; any /8-friendly dummy works). The optimizer
    # is rebuilt from the embedded config purely to shape its state slot.
    n = config["model"].get("args", {}).get("num_frame", 3)
    # channel count comes from the built model (seq adapters derive it from
    # num_bins; 'inch' is absent from their configs)
    inch = int(getattr(model, "inch", 2))
    x = jnp.zeros((1, n, 16, 16, inch), jnp.float32)
    states = model.init_states(1, 16, 16)
    it_cfg = config.get("trainer", {}).get("iteration_based_train", {})
    optimizer, _ = build_optimizer(
        config["optimizer"],
        config.get("lr_scheduler"),
        it_cfg.get("lr_change_rate"),
    )

    def shape_state():
        params = model.init(jax.random.PRNGKey(0), x, states)
        return TrainState.create(params, optimizer)

    template = jax.eval_shape(shape_state)
    abstract = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), template
    )
    ckptr = _checkpointer()
    restored = ckptr.restore(
        os.path.join(os.path.abspath(path), "state"), abstract
    )
    return model, restored.params, config
