from .schedule import exponential_with_floor
from .optim import make_optimizer
from .train_step import make_train_step, TrainState, make_eval_step
from .multistep import make_multi_step
