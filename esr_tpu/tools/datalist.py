"""Train/valid datalist generation over ``*.h5`` globs.

Rebuilds ``/root/reference/datalist/generate_datalist.py:28-108`` as an
importable function + CLI. The four sampling modes are kept (same seeded
``random.sample`` draws so a given seed reproduces the reference's splits):

- mode 0: sample ``num`` training recordings (no validation split);
- mode 1: sample ``num`` training, then ``valid_num`` validation from the
  remainder;
- mode 2: ``portion`` of the glob for training, the rest for validation;
- mode 3: training from ``data_path``, validation from a separate
  ``valid_data_path``.

Usage: ``python -m esr_tpu.tools.datalist --data_path d --mode 2 --portion 0.9``
"""

from __future__ import annotations

import argparse
import glob
import os
import random
from typing import List, Optional, Tuple


def write_txt(path: str, data: List[str]) -> None:
    with open(path, "w") as f:
        f.writelines(str(i) + "\n" for i in data)


def _globbed(path: str) -> List[str]:
    assert os.path.exists(path), path
    return sorted(glob.glob(os.path.join(path, "*.h5")))


def generate_datalist(
    data_path: str,
    mode: int,
    num: Optional[int] = None,
    valid_num: Optional[int] = None,
    portion: Optional[float] = None,
    valid_data_path: Optional[str] = None,
    seed: int = 123,
) -> Tuple[List[str], List[str]]:
    """Returns ``(train_list, valid_list)`` (valid empty for mode 0)."""
    data_paths = _globbed(data_path)
    n = len(data_paths)

    if mode == 0:
        num = n if num is None else num
        assert 0 < num <= n, f"num must be in (0, {n}], got {num}"
        random.seed(seed)
        return sorted(random.sample(data_paths, num)), []

    if mode == 1:
        assert num is not None and valid_num is not None
        assert 0 < num < n and 0 < valid_num < n and num + valid_num <= n
        random.seed(seed)
        train = random.sample(data_paths, num)
        left = sorted(set(data_paths) - set(train))
        random.seed(seed)
        valid = sorted(random.sample(left, valid_num))
        return train, valid

    if mode == 2:
        assert portion is not None
        train_num = int(n * portion)
        random.seed(seed)
        train = random.sample(data_paths, train_num)
        valid = sorted(set(data_paths) - set(train))
        return train, valid

    if mode == 3:
        assert valid_data_path is not None and num is not None and valid_num is not None
        valid_paths = _globbed(valid_data_path)
        random.seed(seed)
        train = sorted(random.sample(data_paths, num))
        random.seed(seed)
        valid = sorted(random.sample(valid_paths, valid_num))
        return train, valid

    raise ValueError(f"invalid mode {mode}")


def main() -> None:
    p = argparse.ArgumentParser(description="generate train/valid datalists")
    p.add_argument("--data_path", required=True)
    p.add_argument("--valid_data_path", default=None)
    p.add_argument("--num", type=int, default=None)
    p.add_argument("--valid_num", type=int, default=None)
    p.add_argument("--portion", type=float, default=None)
    p.add_argument("--mode", type=int, choices=[0, 1, 2, 3], required=True)
    p.add_argument("--seed", type=int, default=123)
    p.add_argument("--out_dir", type=str, default=".")
    p.add_argument("--train_txt_name", type=str, default="train.txt")
    p.add_argument("--valid_txt_name", type=str, default="valid.txt")
    flags = p.parse_args()

    train, valid = generate_datalist(
        flags.data_path,
        flags.mode,
        num=flags.num,
        valid_num=flags.valid_num,
        portion=flags.portion,
        valid_data_path=flags.valid_data_path,
        seed=flags.seed,
    )
    os.makedirs(flags.out_dir, exist_ok=True)
    write_txt(os.path.join(flags.out_dir, flags.train_txt_name), train)
    print(f"wrote {len(train)} training items")
    if valid:
        write_txt(os.path.join(flags.out_dir, flags.valid_txt_name), valid)
        print(f"wrote {len(valid)} validation items")


if __name__ == "__main__":
    main()
