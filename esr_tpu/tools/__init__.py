"""Offline dataset tools: datalist generation, HDF5 packagers, converters."""

from esr_tpu.tools.datalist import generate_datalist, write_txt
from esr_tpu.tools.packagers import H5LadderPackager, H5Packager
from esr_tpu.tools.simulate import (
    EventSimulator,
    convert_eventzoom,
    sample_contrast_thresholds,
    simulate_ladder_recording,
)

__all__ = [
    "generate_datalist",
    "write_txt",
    "H5Packager",
    "H5LadderPackager",
    "EventSimulator",
    "convert_eventzoom",
    "sample_contrast_thresholds",
    "simulate_ladder_recording",
]
