"""Event-camera simulation + dataset generation — no external simulator.

Rebuilds the reference's offline generation pipeline
(``/root/reference/generate_dataset/syn_nfs_rgb.py:70-127``) without its
``esim_py`` C++ dependency: :class:`EventSimulator` is a vectorized numpy
implementation of the ESIM contrast-threshold model (per-pixel log-intensity
reference levels, linearly-interpolated crossing timestamps, refractory
period). The reference's per-sequence random contrast thresholds
(``:114-121``) are reproduced by :func:`sample_contrast_thresholds`.

:func:`simulate_ladder_recording` generates the full multi-resolution
training format: frames are downscaled per ladder rung, events simulated at
every rung with the SAME thresholds (the reference simulates from per-rung
downscaled image folders, ``:122-125``), and everything is written through
:class:`esr_tpu.tools.packagers.H5LadderPackager` — the file the training
pipeline reads directly.

:func:`convert_eventzoom` ports the EventZoom txt->h5 converter
(``convert_eventzoom.py:66-122``: columns ``t x y p`` with p in {0, 1},
mapped to ±1, written as the ori/down2/down4 rungs).
"""

from __future__ import annotations

import os
from glob import glob
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from esr_tpu.tools.packagers import H5LadderPackager

DEFAULT_SIM_CONFIG = {
    # the reference's recipe constants (syn_nfs_rgb config usage :80-121)
    "CT_range": (0.2, 0.5),
    "mu": 1.0,
    "sigma": 0.1,
    "min_CT": 0.01,
    "max_CT": 2.0,
    "refractory_period": 1e-4,
    "log_eps": 1e-3,
    "use_log": True,
}


def sample_contrast_thresholds(
    config: Dict = DEFAULT_SIM_CONFIG, rng: Optional[np.random.Generator] = None
) -> Tuple[float, float]:
    """Per-sequence (Cp, Cn) draw (reference ``syn_nfs_rgb.py:114-121``)."""
    rng = rng or np.random.default_rng()
    cp = rng.uniform(*config["CT_range"])
    cn = rng.normal(config["mu"], config["sigma"]) * cp
    cp = float(np.clip(cp, config["min_CT"], config["max_CT"]))
    cn = float(np.clip(cn, config["min_CT"], config["max_CT"]))
    return cp, cn


class EventSimulator:
    """ESIM contrast-threshold event simulation, vectorized numpy.

    Model: per pixel, a reference level tracks the log intensity at the last
    emitted event; when the (linearly-interpolated) log intensity between two
    frames crosses ``k`` thresholds, ``k`` events fire with timestamps at the
    interpolated crossing times; events within ``refractory_period`` of the
    pixel's previous event are suppressed.
    """

    def __init__(
        self,
        cp: float = 0.3,
        cn: float = 0.3,
        refractory_period: float = 1e-4,
        log_eps: float = 1e-3,
        use_log: bool = True,
    ):
        self.set_parameters(cp, cn, refractory_period, log_eps, use_log)

    def set_parameters(self, cp, cn, refractory_period, log_eps, use_log):
        assert cp > 0 and cn > 0
        self.cp, self.cn = float(cp), float(cn)
        self.refractory_period = float(refractory_period)
        self.log_eps = float(log_eps)
        self.use_log = bool(use_log)

    def _intensity(self, frame: np.ndarray) -> np.ndarray:
        img = np.asarray(frame, np.float64)
        if img.ndim == 3:  # color -> luma
            img = img.mean(axis=-1)
        if img.max() > 1.5:
            img = img / 255.0
        # bicubic downscaling can overshoot below 0 (cv2 INTER_CUBIC) —
        # clamp before the log so intensities stay finite
        img = np.clip(img, 0.0, None)
        return np.log(img + self.log_eps) if self.use_log else img

    def generate_from_frames(
        self, frames: Sequence[np.ndarray], timestamps: Sequence[float]
    ) -> np.ndarray:
        """``frames [T, H, W(, C)]`` + ``timestamps [T]`` -> events
        ``[N, 4]`` (x, y, t, p), globally time-sorted."""
        assert len(frames) == len(timestamps) and len(frames) >= 2
        ts = np.asarray(timestamps, np.float64)
        prev = self._intensity(frames[0])
        h, w = prev.shape
        ref = prev.copy()                      # last-event level per pixel
        last_t = np.full((h, w), -np.inf)      # refractory bookkeeping
        yy, xx = np.mgrid[0:h, 0:w]

        out = []
        for i in range(1, len(frames)):
            cur = self._intensity(frames[i])
            t0, t1 = ts[i - 1], ts[i]
            dlog = cur - prev
            # polarity-dependent threshold per pixel for this frame pair
            for sign, thr in ((1.0, self.cp), (-1.0, self.cn)):
                step = sign * thr
                # number of crossings this pair: how many multiples of
                # `step` lie between ref and cur (moving from prev)
                delta = (cur - ref) * sign
                n_cross = np.floor(delta / thr).astype(np.int64)
                n_cross = np.maximum(n_cross, 0)
                # pixels move monotonically within the pair in this model;
                # only count crossings in the direction of change
                n_cross = np.where(sign * dlog > 0, n_cross, 0)
                max_k = int(n_cross.max()) if n_cross.size else 0
                for k in range(1, max_k + 1):
                    mask = n_cross >= k
                    if not mask.any():
                        break
                    level = ref[mask] + step * k
                    # crossing time: linear interpolation of log intensity
                    frac = (level - prev[mask]) / np.where(
                        dlog[mask] == 0, 1e-12, dlog[mask]
                    )
                    frac = np.clip(frac, 0.0, 1.0)
                    t_ev = t0 + frac * (t1 - t0)
                    keep = t_ev - last_t[mask] >= self.refractory_period
                    xs = xx[mask][keep]
                    ys = yy[mask][keep]
                    tk = t_ev[keep]
                    if tk.size:
                        out.append(
                            np.stack(
                                [xs, ys, tk, np.full(tk.shape, sign)], axis=1
                            )
                        )
                        lt = last_t[mask]
                        lt[keep] = tk
                        last_t[mask] = lt
                # advance the reference level by the crossings consumed
                ref = ref + step * n_cross
            prev = cur

        if not out:
            return np.zeros((0, 4), np.float64)
        events = np.concatenate(out, axis=0)
        return events[np.argsort(events[:, 2], kind="stable")]

    def generate_from_folder(self, folder: str, timestamps_file: str) -> np.ndarray:
        """Mirror of ``esim_py``'s folder API: sorted images + a timestamps
        txt (one float per line)."""
        import cv2

        paths = sorted(
            glob(os.path.join(folder, "*.jpg"))
            + glob(os.path.join(folder, "*.png"))
        )
        ts = np.loadtxt(timestamps_file).reshape(-1)[: len(paths)]
        frames = [cv2.imread(p, cv2.IMREAD_GRAYSCALE) for p in paths]
        return self.generate_from_frames(frames, ts)


_RUNG_FACTOR = {"ori": 1, "down2": 2, "down4": 4, "down8": 8, "down16": 16}


def simulate_ladder_recording(
    frames: Sequence[np.ndarray],
    timestamps: Sequence[float],
    output_path: str,
    rungs: Sequence[str] = ("ori", "down2", "down4", "down8", "down16"),
    sim_config: Dict = DEFAULT_SIM_CONFIG,
    seed: int = 0,
) -> Tuple[float, float]:
    """Frames -> multi-resolution event HDF5 (the training input format).

    Per-rung: frames bicubic-downscaled (the reference pre-builds per-rung
    image folders), events simulated with ONE (Cp, Cn) draw shared across
    rungs (``syn_nfs_rgb.py:114-125``), images + events packaged with
    metadata. Returns the sampled ``(cp, cn)``.
    """
    import cv2

    rng = np.random.default_rng(seed)
    cp, cn = sample_contrast_thresholds(sim_config, rng)
    sim = EventSimulator(
        cp, cn,
        sim_config["refractory_period"],
        sim_config["log_eps"],
        sim_config["use_log"],
    )

    first = np.asarray(frames[0])
    h, w = first.shape[:2]
    with H5LadderPackager(output_path, rungs=rungs) as pk:
        for rung in rungs:
            f = _RUNG_FACTOR[rung]
            rh, rw = round(h / f), round(w / f)
            scaled = [
                cv2.resize(
                    np.asarray(fr), (rw, rh), interpolation=cv2.INTER_CUBIC
                )
                for fr in frames
            ]
            ev = sim.generate_from_frames(scaled, timestamps)
            pk.package_events(rung, ev[:, 0], ev[:, 1], ev[:, 2], ev[:, 3])
            if rung == "ori":
                for idx, (fr, t) in enumerate(zip(scaled, timestamps)):
                    img = np.asarray(fr)
                    if img.ndim == 3:
                        img = img.mean(axis=-1)
                    pk.package_image("ori", img.astype(np.uint8), float(t), idx)
        pk.add_metadata((h, w))
    return cp, cn


def render_scene_frames(
    seed: int,
    num_frames: int = 36,
    h: int = 720,
    w: int = 1280,
    fps: float = 20.0,
    disc_radius_scale: float = 1.0,
) -> Tuple[list, np.ndarray]:
    """Procedurally textured drifting scene -> (uint8 frames [H, W], ts).

    The offline stand-in for the reference's NFS video frames
    (``syn_nfs_rgb.py`` reads real footage; zero-egress images can't): four
    drifting gratings at random orientation/frequency plus high-contrast
    moving discs give the simulator dense brightness changes at every
    ladder rung. Used by ``scripts/make_quality_demo_data.py`` and the
    trained-quality margin test.

    ``disc_radius_scale`` multiplies the disc radii (drawn for the 720p
    default); small-frame callers pass ``min(h, w)/720 + 0.2``-style factors
    explicitly. The default of 1.0 keeps generation bit-reproducible with
    the committed demo corpora.
    """
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)

    n_g = 4
    theta = rng.uniform(0, np.pi, n_g)
    freq = rng.uniform(0.02, 0.12, n_g)  # cycles / pixel
    amp = rng.uniform(0.3, 1.0, n_g)
    vel = rng.uniform(-120, 120, (n_g, 2))  # px / s

    n_b = 6
    cy = rng.uniform(0, h, n_b)
    cx = rng.uniform(0, w, n_b)
    r = rng.uniform(30, 120, n_b) * disc_radius_scale
    bvel = rng.uniform(-150, 150, (n_b, 2))
    bsign = rng.choice([-1.0, 1.0], n_b)

    frames, ts = [], []
    for i in range(num_frames):
        t = i / fps
        img = np.zeros((h, w), np.float32)
        for g in range(n_g):
            ph = (
                (xx - vel[g, 1] * t) * np.cos(theta[g])
                + (yy - vel[g, 0] * t) * np.sin(theta[g])
            ) * (2 * np.pi * freq[g])
            img += amp[g] * np.sin(ph)
        img = (img - img.min()) / (img.max() - img.min() + 1e-9)
        for bi in range(n_b):
            by = (cy[bi] + bvel[bi, 0] * t) % h
            bx = (cx[bi] + bvel[bi, 1] * t) % w
            d2 = (yy - by) ** 2 + (xx - bx) ** 2
            img += bsign[bi] * 0.5 * np.exp(-d2 / (2 * (r[bi] / 2) ** 2))
        img = np.clip(img, 0, 1)
        frames.append((img * 255).astype(np.uint8))
        ts.append(t)
    return frames, np.asarray(ts)


def read_txt_events(path: str) -> np.ndarray:
    """EventZoom txt (``t x y p``, p in {0,1}, one header row) ->
    ``[N, 4]`` (x, y, t, ±1) (reference ``convert_eventzoom.py:66-69,97-102``)."""
    raw = np.loadtxt(path, skiprows=1)
    t, x, y, p = raw[:, 0], raw[:, 1], raw[:, 2], raw[:, 3]
    p = np.where(p == 0, -1.0, p)
    return np.stack([x, y, t, p], axis=1)


def convert_eventzoom(
    root_data_path: str,
    path_to_h5: str,
    sensor_resolution: Tuple[int, int] = (124, 222),
) -> int:
    """EventZoom triple-rate txt dirs -> ladder HDF5 recordings
    (reference ``convert_eventzoom.py:72-122``: ``ev_hr``/``ev_lr_1``/
    ``ev_llr_1`` map to ori/down2/down4)."""
    dirs = {
        "ori": sorted(glob(os.path.join(root_data_path, "data/ev_hr", "*.txt"))),
        "down2": sorted(glob(os.path.join(root_data_path, "data/ev_lr_1", "*.txt"))),
        "down4": sorted(glob(os.path.join(root_data_path, "data/ev_llr_1", "*.txt"))),
    }
    os.makedirs(path_to_h5, exist_ok=True)
    n = 0
    for hr, lr, llr in zip(dirs["ori"], dirs["down2"], dirs["down4"]):
        assert os.path.basename(hr) == os.path.basename(lr) == os.path.basename(llr)
        name = os.path.splitext(os.path.basename(hr))[0] + ".h5"
        with H5LadderPackager(
            os.path.join(path_to_h5, name), rungs=("ori", "down2", "down4")
        ) as pk:
            for rung, path in (("ori", hr), ("down2", lr), ("down4", llr)):
                ev = read_txt_events(path)
                pk.package_events(rung, ev[:, 0], ev[:, 1], ev[:, 2], ev[:, 3])
            pk.add_metadata(sensor_resolution)
        n += 1
    return n
