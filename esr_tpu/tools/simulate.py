"""Event-camera simulation + dataset generation — no external simulator.

Rebuilds the reference's offline generation pipeline
(``/root/reference/generate_dataset/syn_nfs_rgb.py:70-127``) without its
``esim_py`` C++ dependency: :class:`EventSimulator` is a vectorized numpy
implementation of the ESIM contrast-threshold model (per-pixel log-intensity
reference levels, linearly-interpolated crossing timestamps, refractory
period). The reference's per-sequence random contrast thresholds
(``:114-121``) are reproduced by :func:`sample_contrast_thresholds`.

:func:`simulate_ladder_recording` generates the full multi-resolution
training format: frames are downscaled per ladder rung, events simulated at
every rung with the SAME thresholds (the reference simulates from per-rung
downscaled image folders, ``:122-125``), and everything is written through
:class:`esr_tpu.tools.packagers.H5LadderPackager` — the file the training
pipeline reads directly.

:func:`convert_eventzoom` ports the EventZoom txt->h5 converter
(``convert_eventzoom.py:66-122``: columns ``t x y p`` with p in {0, 1},
mapped to ±1, written as the ori/down2/down4 rungs).
"""

from __future__ import annotations

import os
from glob import glob
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from esr_tpu.tools.packagers import H5LadderPackager

DEFAULT_SIM_CONFIG = {
    # the reference's recipe constants (syn_nfs_rgb config usage :80-121)
    "CT_range": (0.2, 0.5),
    "mu": 1.0,
    "sigma": 0.1,
    "min_CT": 0.01,
    "max_CT": 2.0,
    "refractory_period": 1e-4,
    "log_eps": 1e-3,
    "use_log": True,
}


def sample_contrast_thresholds(
    config: Dict = DEFAULT_SIM_CONFIG, rng: Optional[np.random.Generator] = None
) -> Tuple[float, float]:
    """Per-sequence (Cp, Cn) draw (reference ``syn_nfs_rgb.py:114-121``)."""
    rng = rng or np.random.default_rng()
    cp = rng.uniform(*config["CT_range"])
    cn = rng.normal(config["mu"], config["sigma"]) * cp
    cp = float(np.clip(cp, config["min_CT"], config["max_CT"]))
    cn = float(np.clip(cn, config["min_CT"], config["max_CT"]))
    return cp, cn


class EventSimulator:
    """ESIM contrast-threshold event simulation, vectorized numpy.

    Model: per pixel, a reference level tracks the log intensity at the last
    emitted event; when the (linearly-interpolated) log intensity between two
    frames crosses ``k`` thresholds, ``k`` events fire with timestamps at the
    interpolated crossing times; events within ``refractory_period`` of the
    pixel's previous event are suppressed.
    """

    def __init__(
        self,
        cp: float = 0.3,
        cn: float = 0.3,
        refractory_period: float = 1e-4,
        log_eps: float = 1e-3,
        use_log: bool = True,
    ):
        self.set_parameters(cp, cn, refractory_period, log_eps, use_log)

    def set_parameters(self, cp, cn, refractory_period, log_eps, use_log):
        assert cp > 0 and cn > 0
        self.cp, self.cn = float(cp), float(cn)
        self.refractory_period = float(refractory_period)
        self.log_eps = float(log_eps)
        self.use_log = bool(use_log)

    def _intensity(self, frame: np.ndarray) -> np.ndarray:
        img = np.asarray(frame, np.float64)
        if img.ndim == 3:  # color -> luma
            img = img.mean(axis=-1)
        if img.max() > 1.5:
            img = img / 255.0
        # bicubic downscaling can overshoot below 0 (cv2 INTER_CUBIC) —
        # clamp before the log so intensities stay finite
        img = np.clip(img, 0.0, None)
        return np.log(img + self.log_eps) if self.use_log else img

    def generate_from_frames(
        self, frames: Sequence[np.ndarray], timestamps: Sequence[float]
    ) -> np.ndarray:
        """``frames [T, H, W(, C)]`` + ``timestamps [T]`` -> events
        ``[N, 4]`` (x, y, t, p), globally time-sorted."""
        assert len(frames) == len(timestamps) and len(frames) >= 2
        ts = np.asarray(timestamps, np.float64)
        prev = self._intensity(frames[0])
        h, w = prev.shape
        ref = prev.copy()                      # last-event level per pixel
        last_t = np.full((h, w), -np.inf)      # refractory bookkeeping
        yy, xx = np.mgrid[0:h, 0:w]

        out = []
        for i in range(1, len(frames)):
            cur = self._intensity(frames[i])
            t0, t1 = ts[i - 1], ts[i]
            dlog = cur - prev
            # polarity-dependent threshold per pixel for this frame pair
            for sign, thr in ((1.0, self.cp), (-1.0, self.cn)):
                step = sign * thr
                # number of crossings this pair: how many multiples of
                # `step` lie between ref and cur (moving from prev)
                delta = (cur - ref) * sign
                n_cross = np.floor(delta / thr).astype(np.int64)
                n_cross = np.maximum(n_cross, 0)
                # pixels move monotonically within the pair in this model;
                # only count crossings in the direction of change
                n_cross = np.where(sign * dlog > 0, n_cross, 0)
                max_k = int(n_cross.max()) if n_cross.size else 0
                for k in range(1, max_k + 1):
                    mask = n_cross >= k
                    if not mask.any():
                        break
                    level = ref[mask] + step * k
                    # crossing time: linear interpolation of log intensity
                    frac = (level - prev[mask]) / np.where(
                        dlog[mask] == 0, 1e-12, dlog[mask]
                    )
                    frac = np.clip(frac, 0.0, 1.0)
                    t_ev = t0 + frac * (t1 - t0)
                    keep = t_ev - last_t[mask] >= self.refractory_period
                    xs = xx[mask][keep]
                    ys = yy[mask][keep]
                    tk = t_ev[keep]
                    if tk.size:
                        out.append(
                            np.stack(
                                [xs, ys, tk, np.full(tk.shape, sign)], axis=1
                            )
                        )
                        lt = last_t[mask]
                        lt[keep] = tk
                        last_t[mask] = lt
                # advance the reference level by the crossings consumed
                ref = ref + step * n_cross
            prev = cur

        if not out:
            return np.zeros((0, 4), np.float64)
        events = np.concatenate(out, axis=0)
        return events[np.argsort(events[:, 2], kind="stable")]

    def generate_from_folder(self, folder: str, timestamps_file: str) -> np.ndarray:
        """Mirror of ``esim_py``'s folder API: sorted images + a timestamps
        txt (one float per line)."""
        import cv2

        paths = sorted(
            glob(os.path.join(folder, "*.jpg"))
            + glob(os.path.join(folder, "*.png"))
        )
        ts = np.loadtxt(timestamps_file).reshape(-1)[: len(paths)]
        frames = [cv2.imread(p, cv2.IMREAD_GRAYSCALE) for p in paths]
        return self.generate_from_frames(frames, ts)


_RUNG_FACTOR = {"ori": 1, "down2": 2, "down4": 4, "down8": 8, "down16": 16}


def simulate_ladder_recording(
    frames: Sequence[np.ndarray],
    timestamps: Sequence[float],
    output_path: str,
    rungs: Sequence[str] = ("ori", "down2", "down4", "down8", "down16"),
    sim_config: Dict = DEFAULT_SIM_CONFIG,
    seed: int = 0,
) -> Tuple[float, float]:
    """Frames -> multi-resolution event HDF5 (the training input format).

    Per-rung: frames bicubic-downscaled (the reference pre-builds per-rung
    image folders), events simulated with ONE (Cp, Cn) draw shared across
    rungs (``syn_nfs_rgb.py:114-125``), images + events packaged with
    metadata. Returns the sampled ``(cp, cn)``.
    """
    import cv2

    rng = np.random.default_rng(seed)
    cp, cn = sample_contrast_thresholds(sim_config, rng)
    sim = EventSimulator(
        cp, cn,
        sim_config["refractory_period"],
        sim_config["log_eps"],
        sim_config["use_log"],
    )

    first = np.asarray(frames[0])
    h, w = first.shape[:2]
    with H5LadderPackager(output_path, rungs=rungs) as pk:
        for rung in rungs:
            f = _RUNG_FACTOR[rung]
            rh, rw = round(h / f), round(w / f)
            scaled = [
                cv2.resize(
                    np.asarray(fr), (rw, rh), interpolation=cv2.INTER_CUBIC
                )
                for fr in frames
            ]
            ev = sim.generate_from_frames(scaled, timestamps)
            pk.package_events(rung, ev[:, 0], ev[:, 1], ev[:, 2], ev[:, 3])
            if rung == "ori":
                for idx, (fr, t) in enumerate(zip(scaled, timestamps)):
                    img = np.asarray(fr)
                    if img.ndim == 3:
                        img = img.mean(axis=-1)
                    pk.package_image("ori", img.astype(np.uint8), float(t), idx)
        pk.add_metadata((h, w))
    return cp, cn


def render_scene_frames(
    seed: int,
    num_frames: int = 36,
    h: int = 720,
    w: int = 1280,
    fps: float = 20.0,
    disc_radius_scale: float = 1.0,
) -> Tuple[list, np.ndarray]:
    """Procedurally textured drifting scene -> (uint8 frames [H, W], ts).

    The offline stand-in for the reference's NFS video frames
    (``syn_nfs_rgb.py`` reads real footage; zero-egress images can't): four
    drifting gratings at random orientation/frequency plus high-contrast
    moving discs give the simulator dense brightness changes at every
    ladder rung. Used by ``scripts/make_quality_demo_data.py`` and the
    trained-quality margin test.

    ``disc_radius_scale`` multiplies the disc radii (drawn for the 720p
    default); small-frame callers pass ``min(h, w)/720 + 0.2``-style factors
    explicitly. The default of 1.0 keeps generation bit-reproducible with
    the committed demo corpora.
    """
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)

    n_g = 4
    theta = rng.uniform(0, np.pi, n_g)
    freq = rng.uniform(0.02, 0.12, n_g)  # cycles / pixel
    amp = rng.uniform(0.3, 1.0, n_g)
    vel = rng.uniform(-120, 120, (n_g, 2))  # px / s

    n_b = 6
    cy = rng.uniform(0, h, n_b)
    cx = rng.uniform(0, w, n_b)
    r = rng.uniform(30, 120, n_b) * disc_radius_scale
    bvel = rng.uniform(-150, 150, (n_b, 2))
    bsign = rng.choice([-1.0, 1.0], n_b)

    frames, ts = [], []
    for i in range(num_frames):
        t = i / fps
        img = np.zeros((h, w), np.float32)
        for g in range(n_g):
            ph = (
                (xx - vel[g, 1] * t) * np.cos(theta[g])
                + (yy - vel[g, 0] * t) * np.sin(theta[g])
            ) * (2 * np.pi * freq[g])
            img += amp[g] * np.sin(ph)
        img = (img - img.min()) / (img.max() - img.min() + 1e-9)
        for bi in range(n_b):
            by = (cy[bi] + bvel[bi, 0] * t) % h
            bx = (cx[bi] + bvel[bi, 1] * t) % w
            d2 = (yy - by) ** 2 + (xx - bx) ** 2
            img += bsign[bi] * 0.5 * np.exp(-d2 / (2 * (r[bi] / 2) ** 2))
        img = np.clip(img, 0, 1)
        frames.append((img * 255).astype(np.uint8))
        ts.append(t)
    return frames, np.asarray(ts)


def _bilinear_sample(scene: np.ndarray, ys: np.ndarray, xs: np.ndarray) -> np.ndarray:
    """Bilinear gather from ``scene [H, W]`` at float coords (clamped)."""
    hh, ww = scene.shape
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    wy = (ys - y0).astype(np.float32)
    wx = (xs - x0).astype(np.float32)
    y0c = np.clip(y0, 0, hh - 1)
    y1c = np.clip(y0 + 1, 0, hh - 1)
    x0c = np.clip(x0, 0, ww - 1)
    x1c = np.clip(x0 + 1, 0, ww - 1)
    return (
        scene[y0c, x0c] * (1 - wy) * (1 - wx)
        + scene[y0c, x1c] * (1 - wy) * wx
        + scene[y1c, x0c] * wy * (1 - wx)
        + scene[y1c, x1c] * wy * wx
    )


def render_natural_frames(
    seed: int,
    num_frames: int = 36,
    h: int = 360,
    w: int = 640,
    fps: float = 20.0,
    n_leaves: int = 4000,
) -> Tuple[list, np.ndarray]:
    """Natural-statistics scene -> (uint8 frames [H, W], ts).

    The gratings-and-discs renderer (:func:`render_scene_frames`) exercises
    the pipeline but has periodic-texture statistics; the reference's
    quality target is defined on real NFS footage
    (``generate_dataset/syn_nfs_rgb.py:80-127``), which a zero-egress image
    cannot fetch. This renderer synthesizes frames with *natural-image*
    statistics instead (VERDICT r4 "next" item 7):

    - **dead-leaves background**: opaque discs with a power-law radius
      distribution (density ~ r^-3) occluding each other — the classical
      model that reproduces natural images' ~1/f^2 power spectra,
      scale-invariance, and T-junction/occlusion edge statistics (far
      richer than gratings: broadband, aperiodic, edges at all scales);
    - **1/f illumination field** multiplying the albedo (smooth shading);
    - **smooth camera pan + zoom** sampling a margin-padded scene — the
      global optical flow of handheld footage (NFS is hand-tracked video);
    - **independently moving textured foreground objects** for local
      motion/parallax against the camera flow.

    Deterministic per seed. Drop-in for ``render_scene_frames`` in
    ``scripts/make_quality_demo_data.py`` (``DEMO_SCENE=natural``).
    """
    rng = np.random.default_rng(seed)
    margin = 0.25
    hh = int(round(h * (1 + 2 * margin)))
    ww = int(round(w * (1 + 2 * margin)))

    # --- dead-leaves albedo: power-law radii via inverse CDF (p(r)~r^-3
    # => CDF in r^-2), painted back-to-front so later leaves occlude
    r_min, r_max = 2.0, min(hh, ww) / 3.0
    u = rng.uniform(size=n_leaves)
    radii = 1.0 / np.sqrt(u / r_min**2 + (1 - u) / r_max**2)
    cys = rng.uniform(0, hh, n_leaves)
    cxs = rng.uniform(0, ww, n_leaves)
    grays = rng.uniform(0.05, 0.95, n_leaves)
    # mild per-leaf linear gradient: leaves read as lit surfaces, and the
    # interiors aren't piecewise-constant (natural images aren't)
    gdir = rng.uniform(-1, 1, (n_leaves, 2))
    scene = np.full((hh, ww), 0.5, np.float32)
    for i in range(n_leaves):
        ri = radii[i]
        y0, y1 = int(max(0, cys[i] - ri)), int(min(hh, cys[i] + ri + 1))
        x0, x1 = int(max(0, cxs[i] - ri)), int(min(ww, cxs[i] + ri + 1))
        if y0 >= y1 or x0 >= x1:
            continue
        py, px = np.mgrid[y0:y1, x0:x1]
        m = (py - cys[i]) ** 2 + (px - cxs[i]) ** 2 <= ri * ri
        shade = (
            gdir[i, 0] * (py - cys[i]) + gdir[i, 1] * (px - cxs[i])
        ) / (ri + 1.0) * 0.15
        patch = scene[y0:y1, x0:x1]
        patch[m] = np.clip(grays[i] + shade, 0.02, 0.98)[m]

    # --- 1/f illumination (pink noise via spectral shaping)
    fy = np.fft.fftfreq(hh)[:, None]
    fx = np.fft.fftfreq(ww)[None, :]
    f = np.sqrt(fy * fy + fx * fx)
    f[0, 0] = 1.0
    spec = (rng.standard_normal((hh, ww)) + 1j * rng.standard_normal((hh, ww))) / f
    illum = np.real(np.fft.ifft2(spec)).astype(np.float32)
    illum = (illum - illum.mean()) / (illum.std() + 1e-9)
    scene = scene * (1.0 + 0.15 * illum)

    # --- foreground objects: textured discs on straight-line paths
    n_obj = 2
    obj_r = rng.uniform(0.06, 0.12, n_obj) * min(h, w)
    obj_y0 = rng.uniform(0.2, 0.8, n_obj) * h
    obj_x0 = rng.uniform(0.2, 0.8, n_obj) * w
    obj_vel = rng.uniform(-0.22, 0.22, (n_obj, 2)) * min(h, w)  # px/s
    obj_gray = rng.uniform(0.1, 0.9, n_obj)
    obj_phase = rng.uniform(0, 2 * np.pi, n_obj)
    obj_freq = rng.uniform(0.05, 0.15, n_obj)  # texture cycles/px

    # --- camera path: smooth sinusoidal pan within the margin + slow zoom
    pan_amp_y = rng.uniform(0.4, 0.9) * margin * h
    pan_amp_x = rng.uniform(0.4, 0.9) * margin * w
    pan_f = rng.uniform(0.1, 0.3, 2)          # Hz
    pan_ph = rng.uniform(0, 2 * np.pi, 2)
    zoom_amp = rng.uniform(0.02, 0.06)
    zoom_f = rng.uniform(0.08, 0.2)
    zoom_ph = rng.uniform(0, 2 * np.pi)

    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    frames, ts = [], []
    for i in range(num_frames):
        t = i / fps
        zoom = 1.0 + zoom_amp * np.sin(2 * np.pi * zoom_f * t + zoom_ph)
        oy = hh / 2 + pan_amp_y * np.sin(2 * np.pi * pan_f[0] * t + pan_ph[0])
        ox = ww / 2 + pan_amp_x * np.sin(2 * np.pi * pan_f[1] * t + pan_ph[1])
        src_y = oy + (yy - h / 2) * zoom
        src_x = ox + (xx - w / 2) * zoom
        img = _bilinear_sample(scene, src_y, src_x)
        for oi in range(n_obj):
            cy = obj_y0[oi] + obj_vel[oi, 0] * t
            cx = obj_x0[oi] + obj_vel[oi, 1] * t
            d2 = (yy - cy) ** 2 + (xx - cx) ** 2
            m = d2 <= obj_r[oi] ** 2
            if m.any():
                tex = obj_gray[oi] + 0.25 * np.sin(
                    2 * np.pi * obj_freq[oi] * (xx + yy) + obj_phase[oi]
                )
                img = np.where(m, np.clip(tex, 0.02, 0.98), img)
        frames.append((np.clip(img, 0, 1) * 255).astype(np.uint8))
        ts.append(t)
    return frames, np.asarray(ts)


def read_txt_events(path: str) -> np.ndarray:
    """EventZoom txt (``t x y p``, p in {0,1}, one header row) ->
    ``[N, 4]`` (x, y, t, ±1) (reference ``convert_eventzoom.py:66-69,97-102``)."""
    raw = np.loadtxt(path, skiprows=1)
    t, x, y, p = raw[:, 0], raw[:, 1], raw[:, 2], raw[:, 3]
    p = np.where(p == 0, -1.0, p)
    return np.stack([x, y, t, p], axis=1)


def convert_eventzoom(
    root_data_path: str,
    path_to_h5: str,
    sensor_resolution: Tuple[int, int] = (124, 222),
) -> int:
    """EventZoom triple-rate txt dirs -> ladder HDF5 recordings
    (reference ``convert_eventzoom.py:72-122``: ``ev_hr``/``ev_lr_1``/
    ``ev_llr_1`` map to ori/down2/down4)."""
    dirs = {
        "ori": sorted(glob(os.path.join(root_data_path, "data/ev_hr", "*.txt"))),
        "down2": sorted(glob(os.path.join(root_data_path, "data/ev_lr_1", "*.txt"))),
        "down4": sorted(glob(os.path.join(root_data_path, "data/ev_llr_1", "*.txt"))),
    }
    os.makedirs(path_to_h5, exist_ok=True)
    n = 0
    for hr, lr, llr in zip(dirs["ori"], dirs["down2"], dirs["down4"]):
        assert os.path.basename(hr) == os.path.basename(lr) == os.path.basename(llr)
        name = os.path.splitext(os.path.basename(hr))[0] + ".h5"
        with H5LadderPackager(
            os.path.join(path_to_h5, name), rungs=("ori", "down2", "down4")
        ) as pk:
            for rung, path in (("ori", hr), ("down2", lr), ("down4", llr)):
                ev = read_txt_events(path)
                pk.package_events(rung, ev[:, 0], ev[:, 1], ev[:, 2], ev[:, 3])
            pk.add_metadata(sensor_resolution)
        n += 1
    return n
