"""Super-SloMo frame-rate upsampling (offline dataset generation), Flax.

Rebuilds ``/root/reference/generate_dataset/upsampling/utils/model.py:12-283``
and ``upsampler.py:22-228`` (the reference vendors avinashpaliwal/Super-SloMo
and downloads ``SuperSloMo.ckpt`` from the VID2E release — NOT shipped in the
repo either; it is gitignored there):

- :class:`SloMoUNet` — the paper's UNet (7/7/5/3.. kernels, leaky-relu 0.1,
  avg-pool downs, align-corners bilinear ups), NHWC;
- :func:`backwarp` — ``I0 = warp(I1, F_0_1)`` via the framework's
  torch-parity ``grid_sample`` (align_corners=True, matching the vendored
  ``backWarp``);
- :func:`interpolate_frame` — the arbitrary-time interpolation: flow
  mixing coefficients ``[-t(1-t), t², (1-t)², -t(1-t)]``, residual flow +
  visibility from the second UNet, visibility-weighted fusion
  (``upsampler.py:176-205``);
- :func:`upsample_adaptive` — intermediate-frame count from the max flow
  magnitude (``:171-175``), i.e. ~1 px of motion between output frames;
- :func:`convert_superslomo_checkpoint` — one-shot torch ``.ckpt``
  (``state_dictFC``/``state_dictAT``) -> npz; :func:`load_superslomo_npz`
  loads it into the two Flax param trees. Weights must be obtained offline
  (zero-egress image); without them this module is architecture-only.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from esr_tpu.ops.sampling import grid_sample

Array = jax.Array


def _resize_linear_ac(x: Array, oh: int, ow: int) -> Array:
    """align_corners=True bilinear resize via interpolation matrices."""
    b, h, w, c = x.shape

    def mat(n_in, n_out):
        if n_out == 1 or n_in == 1:
            return np.ones((n_out, n_in), np.float32) / n_in
        src = np.arange(n_out) * (n_in - 1) / (n_out - 1)
        i0 = np.floor(src).astype(np.int64)
        i1 = np.minimum(i0 + 1, n_in - 1)
        f = src - i0
        m = np.zeros((n_out, n_in), np.float32)
        m[np.arange(n_out), i0] += 1 - f
        m[np.arange(n_out), i1] += f
        return m

    my = jnp.asarray(mat(h, oh))
    mx = jnp.asarray(mat(w, ow))
    out = jnp.einsum("oh,bhwc->bowc", my, x)
    return jnp.einsum("pw,bowc->bopc", mx, out)


class _Down(nn.Module):
    """avg-pool 2 -> conv+lrelu -> conv+lrelu (reference ``down``, :12-73)."""

    features: int
    kernel_size: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        k = self.kernel_size
        p = (k - 1) // 2
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(self.features, (k, k), padding=((p, p), (p, p)), name="conv1")(x)
        x = jax.nn.leaky_relu(x, 0.1)
        x = nn.Conv(self.features, (k, k), padding=((p, p), (p, p)), name="conv2")(x)
        return jax.nn.leaky_relu(x, 0.1)


class _Up(nn.Module):
    """bilinear x2 (align-corners) -> conv+lrelu -> conv(cat skip)+lrelu
    (reference ``up``, :76-133)."""

    features: int

    @nn.compact
    def __call__(self, x: Array, skip: Array) -> Array:
        x = _resize_linear_ac(x, 2 * x.shape[1], 2 * x.shape[2])
        x = nn.Conv(self.features, (3, 3), padding=((1, 1), (1, 1)), name="conv1")(x)
        x = jax.nn.leaky_relu(x, 0.1)
        x = nn.Conv(
            self.features, (3, 3), padding=((1, 1), (1, 1)), name="conv2"
        )(jnp.concatenate([x, skip], axis=-1))
        return jax.nn.leaky_relu(x, 0.1)


class SloMoUNet(nn.Module):
    """The Super-SloMo UNet (reference ``UNet``, :136-207)."""

    out_channels: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = jax.nn.leaky_relu(
            nn.Conv(32, (7, 7), padding=((3, 3), (3, 3)), name="conv1")(x), 0.1
        )
        s1 = jax.nn.leaky_relu(
            nn.Conv(32, (7, 7), padding=((3, 3), (3, 3)), name="conv2")(x), 0.1
        )
        s2 = _Down(64, 5, name="down1")(s1)
        s3 = _Down(128, 3, name="down2")(s2)
        s4 = _Down(256, 3, name="down3")(s3)
        s5 = _Down(512, 3, name="down4")(s4)
        x = _Down(512, 3, name="down5")(s5)
        x = _Up(512, name="up1")(x, s5)
        x = _Up(256, name="up2")(x, s4)
        x = _Up(128, name="up3")(x, s3)
        x = _Up(64, name="up4")(x, s2)
        x = _Up(32, name="up5")(x, s1)
        x = nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)), name="conv3")(x)
        return jax.nn.leaky_relu(x, 0.1)


def backwarp(img: Array, flow: Array) -> Array:
    """``I0 = backwarp(I1, F_0_1)`` — sample ``img [B, H, W, C]`` at
    ``grid + flow [B, H, W, 2]`` (flow channels (u, v)); align_corners=True
    normalization (reference ``backWarp``, :210-283)."""
    b, h, w, c = img.shape
    gx = jnp.arange(w, dtype=jnp.float32)[None, None, :] + flow[..., 0]
    gy = jnp.arange(h, dtype=jnp.float32)[None, :, None] + flow[..., 1]
    grid = jnp.stack(
        [2 * (gx / w - 0.5), 2 * (gy / h - 0.5)], axis=-1
    )
    return grid_sample(img, grid, align_corners=True)


def interpolate_frame(
    flow_params,
    interp_params,
    i0: Array,
    i1: Array,
    t: float,
    flows: Optional[Tuple[Array, Array]] = None,
) -> Array:
    """One intermediate frame at relative time ``t`` in (0, 1)
    (reference ``_upsample_adaptive`` body, ``upsampler.py:176-205``)."""
    fc = SloMoUNet(out_channels=4)
    at = SloMoUNet(out_channels=5)

    if flows is None:
        flow_out = fc.apply(flow_params, jnp.concatenate([i0, i1], axis=-1))
        f01, f10 = flow_out[..., :2], flow_out[..., 2:]
    else:
        f01, f10 = flows

    temp = -t * (1 - t)
    ft0 = temp * f01 + (t * t) * f10
    ft1 = ((1 - t) * (1 - t)) * f01 + temp * f10

    g0 = backwarp(i0, ft0)
    g1 = backwarp(i1, ft1)
    interp_out = at.apply(
        interp_params,
        jnp.concatenate([i0, i1, f01, f10, ft1, ft0, g1, g0], axis=-1),
    )
    ft0_f = interp_out[..., :2] + ft0
    ft1_f = interp_out[..., 2:4] + ft1
    v0 = jax.nn.sigmoid(interp_out[..., 4:5])
    v1 = 1 - v0

    g0f = backwarp(i0, ft0_f)
    g1f = backwarp(i1, ft1_f)
    w0, w1 = 1 - t, t
    return (w0 * v0 * g0f + w1 * v1 * g1f) / (w0 * v0 + w1 * v1 + 1e-12)


def upsample_adaptive(
    flow_params, interp_params, i0: Array, i1: Array, t0: float, t1: float
) -> Tuple[List[np.ndarray], List[float]]:
    """Adaptive interpolation: one output frame per ~pixel of peak motion
    (reference ``:171-205``). Returns (frames, timestamps), excluding i1."""
    fc = SloMoUNet(out_channels=4)
    flow_out = fc.apply(flow_params, jnp.concatenate([i0, i1], axis=-1))
    f01, f10 = flow_out[..., :2], flow_out[..., 2:]
    n = int(np.ceil(float(jnp.maximum(
        jnp.sqrt((f01**2).sum(-1)).max(), jnp.sqrt((f10**2).sum(-1)).max()
    ))))
    frames = [np.asarray(i0[0])]
    stamps = [t0]
    for k in range(1, max(n, 1)):
        t = k / n
        ft = interpolate_frame(
            flow_params, interp_params, i0, i1, t, flows=(f01, f10)
        )
        frames.append(np.asarray(ft[0]))
        stamps.append(t0 + t * (t1 - t0))
    return frames, stamps


# -- weight conversion -------------------------------------------------------

_TORCH_TO_FLAX = None  # computed lazily


def _torch_key_map() -> Dict[str, Tuple[str, ...]]:
    """torch state-dict key -> flax param path for :class:`SloMoUNet`."""
    mapping: Dict[str, Tuple[str, ...]] = {}
    for tk, fk in (("conv1", "conv1"), ("conv2", "conv2"), ("conv3", "conv3")):
        mapping[f"{tk}.weight"] = (fk, "kernel")
        mapping[f"{tk}.bias"] = (fk, "bias")
    for i in range(1, 6):
        for c in ("conv1", "conv2"):
            mapping[f"down{i}.{c}.weight"] = (f"down{i}", c, "kernel")
            mapping[f"down{i}.{c}.bias"] = (f"down{i}", c, "bias")
            mapping[f"up{i}.{c}.weight"] = (f"up{i}", c, "kernel")
            mapping[f"up{i}.{c}.bias"] = (f"up{i}", c, "bias")
    return mapping


def convert_superslomo_checkpoint(ckpt_path: str, out_npz_path: str) -> None:
    """torch ``SuperSloMo.ckpt`` -> flat npz (run offline where torch can
    read the download; reference loads it at ``upsampler.py:45-69``)."""
    import torch

    ckpt = torch.load(ckpt_path, map_location="cpu")
    out = {}
    for name, sd in (("fc", ckpt["state_dictFC"]), ("at", ckpt["state_dictAT"])):
        for k, v in sd.items():
            out[f"{name}.{k}"] = v.numpy()
    np.savez(out_npz_path, **out)


def load_superslomo_npz(npz_path: str) -> Tuple[Dict, Dict]:
    """npz -> ``(flow_params, interp_params)`` flax trees (OIHW -> HWIO)."""
    data = np.load(npz_path)
    key_map = _torch_key_map()

    def build(prefix: str) -> Dict:
        params: Dict = {}
        for tk, path in key_map.items():
            full = f"{prefix}.{tk}"
            if full not in data.files:
                raise KeyError(f"missing weight {full}")
            v = data[full]
            if v.ndim == 4:
                v = np.transpose(v, (2, 3, 1, 0))
            node = params
            for p in path[:-1]:
                node = node.setdefault(p, {})
            node[path[-1]] = v
        return {"params": params}

    return build("fc"), build("at")
