"""Small HDF5 maintenance tools: txt import, attribute editing, memmap export.

Rebuilds the remaining offline utilities of
``/root/reference/generate_dataset/tools/``:

- :func:`extract_txt_to_h5` — generic event txt (``t x y p``, optional
  ``width height`` header row) -> single-stream HDF5 via
  :class:`~esr_tpu.tools.packagers.H5Packager`, chunked so arbitrarily long
  files stream in O(chunk) memory (``txt_to_h5.py:24-103``);
- :func:`add_hdf5_attribute` — batch attribute editing over files/dirs/lists
  (``add_hdf5_attribute.py:28-36``);
- :func:`h5_to_memmap` — events + frames exported as raw ``np.memmap``
  arrays + ``metadata.json`` (``h5_to_memmap.py:16-134``);
- :func:`read_h5_summary` — quick inspection of a recording
  (``read_events.py``);
- :func:`read_h5_events` / :func:`read_h5_event_components` — whole-recording
  event readers incl. the legacy ``events/x`` key scheme
  (``read_events.py:59-75``);
- :func:`read_memmap` — loader for the :func:`h5_to_memmap` layout
  (``read_events.py:10-57`` reads the same tree);
- :func:`events_to_ply` — event cloud -> binary PLY point cloud for external
  3D viewers (``myutils/vis_events/tools/hxy_events2ply.py``), written
  dependency-free (no ``plyfile`` in this image);
- :func:`validate_frame_sizes` — frame-directory sanity check preceding
  packaging (``generate_dataset/test_size.py``).

- :func:`extract_rosbag_to_h5` / :func:`extract_rosbags_to_h5` — rosbag
  event/image/flow topics -> packaged h5 (``rosbag_to_h5.py:44-155``).
  Needs only the ``rosbag`` reader module (not the full ROS vision stack —
  images decode without cv_bridge); raises a clear ImportError when
  ``rosbag`` is absent, as in this image.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from esr_tpu.tools.packagers import H5Packager


def get_filepaths(path: str, extensions: Sequence[str] = (".h5", ".hdf")) -> List[str]:
    """Path / directory / list-file -> file list
    (``add_hdf5_attribute.py:13-26``)."""
    path = path.rstrip("/")
    if os.path.isdir(path):
        out: List[str] = []
        for ext in extensions:
            out += sorted(glob.glob(os.path.join(path, f"*{ext}")))
        return out
    if any(path.endswith(e) for e in extensions):
        return [path]
    with open(path) as f:
        return [line.strip() for line in f if line.strip()]


def add_hdf5_attribute(
    paths: Sequence[str], group: str, name: str, value, dry_run: bool = False
) -> None:
    import h5py

    for p in paths:
        print(f"adding {p}/{group}[{name}]={value}")
        if dry_run:
            continue
        with h5py.File(p, "a") as f:
            target = f[group] if group else f
            target.attrs[name] = value


def extract_txt_to_h5(
    txt_path: str,
    output_path: str,
    zero_timestamps: bool = False,
    chunksize: int = 100_000,
    sensor_size: Optional[Tuple[int, int]] = None,
) -> Tuple[int, int]:
    """Stream a ``t x y p`` event txt into a single-stream HDF5.

    First line may carry ``width height``; polarity 0 is mapped to -1.
    Returns ``(num_pos, num_neg)``.
    """
    if sensor_size is None:
        try:
            with open(txt_path) as f:
                w, h = (int(v) for v in f.readline().split()[:2])
            sensor_size = (h, w)
        except Exception:
            sensor_size = None

    pk = H5Packager(output_path)
    num_pos = num_neg = 0
    t0 = None
    last_t = 0.0
    max_x = max_y = 0
    with open(txt_path) as f:
        f.readline()  # header
        while True:
            rows = []
            for _ in range(chunksize):
                line = f.readline()
                if not line:
                    break
                rows.append(line.split())
            if not rows:
                break
            arr = np.asarray(rows, np.float64)
            ts, xs, ys, ps = arr[:, 0], arr[:, 1], arr[:, 2], arr[:, 3]
            ps = np.where(ps == 0, -1.0, np.sign(ps))
            if t0 is None:
                t0 = float(ts[0])
            if zero_timestamps:
                ts = ts - t0
            pk.package_events(
                xs.astype(np.int16), ys.astype(np.int16), ts, ps
            )
            num_pos += int((ps > 0).sum())
            num_neg += int((ps < 0).sum())
            last_t = float(ts[-1])
            max_x = max(max_x, int(xs.max()))
            max_y = max(max_y, int(ys.max()))
    if sensor_size is None:
        sensor_size = (max_y + 1, max_x + 1)
    pk.add_metadata(
        num_pos, num_neg, 0.0 if zero_timestamps else (t0 or 0.0), last_t,
        sensor_size,
    )
    pk.close()
    return num_pos, num_neg


def h5_to_memmap(h5_path: str, output_dir: str, overwrite: bool = True) -> str:
    """Export a single-stream recording as raw memmaps
    (``h5_to_memmap.py:63-134``): ``t.npy`` float64 [N,1], ``xy.npy`` int16
    [N,2], ``p.npy`` bool [N,1], per-image stacks + timestamps + event
    indices, and the file attrs as ``metadata.json``."""
    import h5py

    if os.path.exists(output_dir):
        if not overwrite:
            raise FileExistsError(output_dir)
        shutil.rmtree(output_dir)
    mmap_dir = os.path.join(output_dir, "memmap")
    os.makedirs(mmap_dir)

    with h5py.File(h5_path, "r") as f:
        n = f["events/ts"].shape[0]
        t = np.memmap(os.path.join(mmap_dir, "t.npy"), "float64", "w+", shape=(n, 1))
        xy = np.memmap(os.path.join(mmap_dir, "xy.npy"), "int16", "w+", shape=(n, 2))
        p = np.memmap(os.path.join(mmap_dir, "p.npy"), "bool", "w+", shape=(n, 1))
        t[:, 0] = f["events/ts"][:]
        xy[:, 0] = f["events/xs"][:]
        xy[:, 1] = f["events/ys"][:]
        p[:, 0] = np.asarray(f["events/ps"][:]) > 0
        t.flush(); xy.flush(); p.flush()

        images_shape = None
        if "images" in f:
            names = sorted(f["images"])
            if names:
                first = f[f"images/{names[0]}"]
                h, w = first.attrs["size"][:2]
                c = 1 if len(first.attrs["size"]) <= 2 else first.attrs["size"][2]
                images_shape = [len(names), int(h), int(w), int(c)]
                imgs = np.memmap(
                    os.path.join(mmap_dir, "images.npy"), "uint8", "w+",
                    shape=tuple(images_shape),
                )
                img_ts = np.memmap(
                    os.path.join(mmap_dir, "timestamps.npy"), "float64", "w+",
                    shape=(len(names), 1),
                )
                idxs = np.memmap(
                    os.path.join(mmap_dir, "image_event_indices.npy"),
                    "uint64", "w+", shape=(len(names), 1),
                )
                for i, name in enumerate(names):
                    d = f[f"images/{name}"]
                    imgs[i] = np.asarray(d[:]).reshape(int(h), int(w), int(c))
                    img_ts[i, 0] = d.attrs["timestamp"]
                    idxs[i, 0] = d.attrs.get("event_idx", 0)
                imgs.flush(); img_ts.flush(); idxs.flush()

        meta = {
            k: (v.tolist() if isinstance(v, np.ndarray) else
                v.item() if isinstance(v, np.generic) else v)
            for k, v in f.attrs.items()
        }
        meta["num_events"] = int(meta.get("num_events", n))
        if images_shape is not None:
            meta["images_shape"] = images_shape
    with open(os.path.join(mmap_dir, "metadata.json"), "w") as js:
        json.dump(meta, js)
    return mmap_dir


def read_h5_summary(h5_path: str) -> Dict:
    """Quick recording inspection (``read_events.py`` role): attrs + per-group
    event counts."""
    import h5py

    out: Dict = {"attrs": {}, "groups": {}}
    with h5py.File(h5_path, "r") as f:
        for k, v in f.attrs.items():
            out["attrs"][k] = v.tolist() if isinstance(v, np.ndarray) else v
        for key in f:
            if key.endswith("_events") or key == "events":
                out["groups"][key] = int(f[f"{key}/ts"].shape[0])
            elif key.endswith("images") or key == "images":
                out["groups"][key] = len(f[key])
    return out


def read_h5_event_components(
    h5_path: str, group: str = "events"
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """``(xs, ys, ts, ps)`` for a whole recording, ``ps`` in {+1, -1};
    accepts both the current ``xs/ys/ts/ps`` keys and the legacy
    ``x/y/ts/p`` scheme (``read_events.py:68-75``)."""
    import h5py

    with h5py.File(h5_path, "r") as f:
        if f"{group}/x" in f:  # legacy
            return (
                f[f"{group}/x"][:], f[f"{group}/y"][:], f[f"{group}/ts"][:],
                np.where(np.asarray(f[f"{group}/p"][:]) > 0, 1, -1),
            )
        return (
            f[f"{group}/xs"][:], f[f"{group}/ys"][:], f[f"{group}/ts"][:],
            np.where(np.asarray(f[f"{group}/ps"][:]) > 0, 1, -1),
        )


def read_h5_events(h5_path: str, group: str = "events") -> np.ndarray:
    """``[N, 4]`` ``(x, y, t, p)`` stack (``read_events.py:59-66``)."""
    xs, ys, ts, ps = read_h5_event_components(h5_path, group)
    return np.stack([xs, ys, ts, ps], axis=1).astype(np.float64)


def read_memmap(mmap_dir: str, return_events: bool = False) -> Dict:
    """Load a :func:`h5_to_memmap` directory back as (mem-mapped) arrays
    (role of ``read_events.py:read_memmap_events``, ``:10-57``).

    Shapes are recovered from the file sizes plus ``metadata.json`` (the
    arrays are raw memmaps, not ``.npy``-with-header). With
    ``return_events=False`` the event arrays stay memory-mapped."""
    with open(os.path.join(mmap_dir, "metadata.json")) as js:
        meta = json.load(js)
    n = os.path.getsize(os.path.join(mmap_dir, "t.npy")) // 8
    data: Dict = {"metadata": meta, "num_events": n, "path": mmap_dir}
    t = np.memmap(os.path.join(mmap_dir, "t.npy"), "float64", "r", shape=(n, 1))
    xy = np.memmap(os.path.join(mmap_dir, "xy.npy"), "int16", "r", shape=(n, 2))
    p = np.memmap(os.path.join(mmap_dir, "p.npy"), "bool", "r", shape=(n, 1))
    if return_events:
        data["t"], data["xy"], data["p"] = t[:], xy[:], p[:]
    else:
        data["t"], data["xy"], data["p"] = t, xy, p
    data["t0"] = float(t[0, 0]) if n else 0.0

    ts_path = os.path.join(mmap_dir, "timestamps.npy")
    if os.path.exists(ts_path):
        n_img = os.path.getsize(ts_path) // 8
        data["frame_stamps"] = np.memmap(ts_path, "float64", "r", shape=(n_img, 1))
        data["index"] = np.memmap(
            os.path.join(mmap_dir, "image_event_indices.npy"),
            "uint64", "r", shape=(n_img, 1),
        )
        img_path = os.path.join(mmap_dir, "images.npy")
        shape = meta.get("images_shape")
        if shape is None and os.path.exists(img_path):
            # pre-images_shape exports: frames were written at sensor size
            res = meta.get("sensor_resolution")
            if res is not None:
                h, w = int(res[0]), int(res[1])
                denom = n_img * h * w
                size = os.path.getsize(img_path)
                c = size // max(denom, 1)
                # only trust the inference when the file divides exactly —
                # frames not at sensor size (or a truncated file) would
                # otherwise make np.memmap raise instead of skipping images
                if c > 0 and c * denom == size:
                    shape = [n_img, h, w, c]
        if shape is not None and os.path.exists(img_path):
            data["images"] = np.memmap(
                img_path, "uint8", "r", shape=tuple(shape)
            )
    return data


def events_to_ply(
    events: np.ndarray,
    resolution: Tuple[int, int],
    output_path: str,
    text: bool = False,
) -> int:
    """Event cloud -> PLY point cloud (``hxy_events2ply.py:22-71``): vertices
    ``(x, y, z=t)`` with ``t`` min-max-normalized to the sensor height so the
    cloud is roughly cubic, colored red=positive / blue=negative. Written as
    binary-little-endian (or ASCII with ``text=True``) without ``plyfile``.

    ``events``: ``[N, 4]`` ``(x, y, t, p)``, ``p`` in {+1, -1}.
    Returns the number of vertices written.
    """
    events = np.asarray(events)
    n = len(events)
    xs = events[:, 0].astype("<f4")
    ys = events[:, 1].astype("<f4")
    ts = events[:, 2].astype(np.float64)
    ps = events[:, 3]
    if n:
        rng = ts.max() - ts.min()
        ts = (ts - ts.min()) / (rng if rng else 1.0) * resolution[0]

    vertices = np.empty(
        n,
        dtype=[("x", "<f4"), ("y", "<f4"), ("z", "<f4"),
               ("red", "u1"), ("green", "u1"), ("blue", "u1")],
    )
    vertices["x"] = xs
    vertices["y"] = ys
    vertices["z"] = ts.astype("<f4")
    vertices["red"] = np.where(ps > 0, 255, 0).astype("u1")
    vertices["green"] = 0
    vertices["blue"] = np.where(ps < 0, 255, 0).astype("u1")

    fmt = "ascii" if text else "binary_little_endian"
    header = (
        f"ply\nformat {fmt} 1.0\nelement vertex {n}\n"
        "property float x\nproperty float y\nproperty float z\n"
        "property uchar red\nproperty uchar green\nproperty uchar blue\n"
        "end_header\n"
    )
    with open(output_path, "wb") as f:
        f.write(header.encode("ascii"))
        if text:
            for v in vertices:
                f.write(
                    f"{v['x']:g} {v['y']:g} {v['z']:g} "
                    f"{v['red']} {v['green']} {v['blue']}\n".encode("ascii")
                )
        else:
            f.write(vertices.tobytes())
    return n


def validate_frame_sizes(
    root: str, expected: Tuple[int, int] = (720, 1280), pattern: str = "*.jpg"
) -> Dict[str, List[str]]:
    """Frame-dataset sanity check (reference
    ``generate_dataset/test_size.py:11-20``): EVERY frame must be landscape
    and match ``expected`` (H, W); unreadable frames are flagged too.
    Returns ``{'portrait': [...], 'mismatched': [...], 'unreadable': [...]}``
    of offending sequence directories."""
    import cv2

    bad: Dict[str, List[str]] = {"portrait": [], "mismatched": [], "unreadable": []}
    for dirpath, _, _ in os.walk(root):
        frames = sorted(glob.glob(os.path.join(dirpath, pattern)))
        if not frames:
            continue
        flags = set()
        for fp in frames:
            img = cv2.imread(fp)
            if img is None:
                flags.add("unreadable")
                continue
            h, w = img.shape[:2]
            if h > w:
                flags.add("portrait")
            if (h, w) != tuple(expected):
                flags.add("mismatched")
        for k in flags:
            bad[k].append(dirpath)
    return bad


def _ros_stamp_to_float(stamp) -> float:
    """ROS ``Time`` -> float seconds (reference ``rosbag_to_h5.py:21-22``)."""
    return stamp.secs + stamp.nsecs / 1e9


def _decode_ros_image(msg, is_color: bool) -> np.ndarray:
    """Decode a ``sensor_msgs/Image`` without cv_bridge.

    The reference routes every frame through ``CvBridge().imgmsg_to_cv2``
    (``rosbag_to_h5.py:84-87``); this build decodes the raw buffer directly
    (mono8 / bgr8 / rgb8 cover event-camera bags) so the converter needs only
    ``rosbag`` itself, not the full ROS vision stack. Output matches the
    reference convention: ``mono8`` (H, W) unless ``is_color``, else ``bgr8``
    (H, W, 3).
    """
    enc = getattr(msg, "encoding", "mono8")
    buf = np.frombuffer(bytes(msg.data), np.uint8)

    def rows(channels: int) -> np.ndarray:
        # honor the row stride (sensor_msgs/Image.step — alignment padding
        # is common for widths that aren't a multiple of 4); cv_bridge does
        # the same. A missing/zero step means tightly packed.
        step = int(getattr(msg, "step", 0)) or msg.width * channels
        img = buf.reshape(msg.height, step)[:, : msg.width * channels]
        return img.reshape(msg.height, msg.width, channels)

    if enc == "mono8":
        img = rows(1)[..., 0]
        if is_color:
            img = np.repeat(img[..., None], 3, axis=-1)
        return img
    if enc in ("bgr8", "rgb8"):
        img = rows(3)
        if enc == "rgb8":
            img = img[..., ::-1]  # reference output convention is bgr8
        if not is_color:
            # ITU-R BT.601 luma, same weights AND rounding as
            # cv_bridge/OpenCV (cvtColor rounds; truncation would differ
            # by 1 LSB on ~half of all pixels)
            b, g, r = img[..., 0], img[..., 1], img[..., 2]
            img = np.rint(
                0.114 * b + 0.587 * g + 0.299 * r
            ).astype(np.uint8)
        return img
    raise ValueError(f"unsupported image encoding {enc!r}")


def extract_rosbag_to_h5(
    rosbag_path: str,
    output_path: str,
    event_topic: str = "/dvs/events",
    image_topic: Optional[str] = None,
    flow_topic: Optional[str] = None,
    start_time: Optional[float] = None,
    end_time: Optional[float] = None,
    zero_timestamps: bool = False,
    is_color: bool = False,
    sensor_size: Optional[Tuple[int, int]] = None,
) -> Dict[str, float]:
    """Stream one rosbag's event/image/flow topics into the packaged h5.

    Rebuilds the reference converter
    (``generate_dataset/tools/rosbag_to_h5.py:44-144``) on
    :class:`~esr_tpu.tools.packagers.H5Packager`: events are appended
    per-message (never buffered whole), images/flows are written as they
    arrive, and the final metadata records counts, t0/tk and the sensor
    resolution. Returns a stats dict
    ``{num_pos, num_neg, num_imgs, num_flow, t0, last_ts}``.

    Deliberate deviations from the reference, by behavior:

    - ``zero_timestamps`` + default ``start_time``: the reference sets
      ``start_time = first_ts`` (absolute) while comparing it against
      already-zeroed timestamps (``rosbag_to_h5.py:66-79,111-112``), which
      filters out every event; here the default window opens at the first
      observed timestamp in the SAME time base as the filter.
    - sensor-size inference from events grows as ``(max_y+1, max_x+1)``
      (coordinates are 0-based) instead of the reference's ``[max(xs),
      max(ys)]`` with transposed comparisons (``:135-136``).
    - images decode without cv_bridge (see :func:`_decode_ros_image`).

    Requires only the ``rosbag`` reader API: ``Bag.read_messages()`` yielding
    ``(topic, msg, t)`` — any module providing that duck-type works (the test
    suite injects a synthetic one).
    """
    try:
        import rosbag
    except ImportError as e:
        raise ImportError(
            "rosbag conversion needs the ROS python stack (rosbag); install "
            "ROS or convert offline with the reference tooling, then import "
            "the h5 here."
        ) from e

    from esr_tpu.tools.packagers import H5Packager

    if not os.path.exists(rosbag_path):
        raise FileNotFoundError(rosbag_path)

    topics = (event_topic, image_topic, flow_topic)
    first_ts = None
    num_pos = num_neg = img_cnt = flow_cnt = 0
    last_ts = 0.0
    t0 = 0.0
    # An explicit sensor_size is authoritative (recorded as-is); otherwise
    # it is inferred and only ever GROWS per dimension.
    size_fixed = sensor_size is not None
    size = tuple(sensor_size) if size_fixed else None

    with H5Packager(output_path) as ep, rosbag.Bag(rosbag_path, "r") as bag:
        for topic, msg, _t in bag.read_messages():
            if topic not in topics:
                continue
            if first_ts is None:
                stamp = getattr(msg, "header", None)
                if stamp is not None:
                    first_ts = _ros_stamp_to_float(stamp.stamp)
                elif getattr(msg, "events", None):
                    first_ts = _ros_stamp_to_float(msg.events[0].ts)
                else:
                    continue  # header-less empty packet: no time base yet
                if start_time is None:
                    start_time = 0.0 if zero_timestamps else first_ts
                if end_time is None:
                    end_time = float("inf")
                t0 = start_time

            off = first_ts if zero_timestamps else 0.0

            if topic == image_topic:
                ts = _ros_stamp_to_float(msg.header.stamp) - off
                if start_time <= ts <= end_time:
                    image = _decode_ros_image(msg, is_color)
                    ep.package_image(image, ts, img_cnt)
                    if not size_fixed:
                        # same only-ever-grows rule as the event branch, so
                        # arrival order can never shrink the recorded size
                        ih, iw = image.shape[:2]
                        size = (ih, iw) if size is None else (
                            max(size[0], ih), max(size[1], iw)
                        )
                    img_cnt += 1
            elif topic == flow_topic:
                ts = _ros_stamp_to_float(msg.header.stamp) - off
                if start_time <= ts <= end_time:
                    flow_x = np.asarray(msg.flow_x, np.float32).reshape(
                        msg.height, msg.width
                    )
                    flow_y = np.asarray(msg.flow_y, np.float32).reshape(
                        msg.height, msg.width
                    )
                    ep.package_flow(
                        np.stack((flow_x, flow_y), axis=0), ts, flow_cnt
                    )
                    flow_cnt += 1
            elif topic == event_topic:
                xs, ys, ts_, ps = [], [], [], []
                for e in msg.events:
                    ts = _ros_stamp_to_float(e.ts) - off
                    if start_time <= ts <= end_time:
                        xs.append(e.x)
                        ys.append(e.y)
                        ts_.append(ts)
                        ps.append(1 if e.polarity else 0)
                        if e.polarity:
                            num_pos += 1
                        else:
                            num_neg += 1
                        last_ts = ts
                if xs:
                    if not size_fixed:
                        grown = (max(ys) + 1, max(xs) + 1)
                        size = grown if size is None else (
                            max(size[0], grown[0]), max(size[1], grown[1])
                        )
                    ep.package_events(xs, ys, ts_, ps)
                # events arrive time-ordered: once the last event in a
                # message is past the window, stop reading the bag
                # (reference ``:133-134`` returns without metadata; writing
                # the metadata for the collected prefix is strictly better)
                if msg.events and ts > end_time:
                    break
        if num_pos + num_neg == 0:
            # no event passed the window: tk would otherwise keep its 0.0
            # initializer and write a negative duration for t0 > 0 bags
            last_ts = t0
        ep.add_metadata(num_pos, num_neg, t0, last_ts, size or (0, 0))
    return {
        "num_pos": num_pos,
        "num_neg": num_neg,
        "num_imgs": img_cnt,
        "num_flow": flow_cnt,
        "t0": t0,
        "last_ts": last_ts,
        "sensor_size": size,
    }


def extract_rosbags_to_h5(
    rosbag_paths: Sequence[str], output_dir: str, **kwargs
) -> List[str]:
    """Batch driver (reference ``rosbag_to_h5.py:147-155``): one h5 per bag,
    named after the bag."""
    os.makedirs(output_dir, exist_ok=True)
    outs = []
    for path in rosbag_paths:
        bagname = os.path.splitext(os.path.basename(path))[0]
        out_path = os.path.join(output_dir, f"{bagname}.h5")
        extract_rosbag_to_h5(path, out_path, **kwargs)
        outs.append(out_path)
    return outs
