"""HDF5 recording packagers — produce the framework's input format.

Rebuilds ``/root/reference/generate_dataset/tools/event_packagers.py``:

- :class:`H5Packager` — single-stream layout (``events/{xs,ys,ts,ps}``,
  ``images/image%09d``, flow, metadata attrs, ``event_idx`` back-references;
  reference ``:37-117``);
- :class:`H5LadderPackager` — the multi-resolution layout the training
  pipeline reads (``{prefix}_events/...`` + ``{prefix}_images/...`` per
  ladder rung; reference ``:119+`` spells each rung as a copy-pasted block,
  here it's one loop over ``rungs``).

Both buffer appends host-side and write chunked, resizable datasets, so
packaging streams of arbitrary length is O(1) memory.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

DEFAULT_RUNGS = ("ori", "down2", "down4", "down8", "down16")


def _h5py():
    import h5py

    return h5py


class _EventGroup:
    """Resizable xs/ys/ts/ps datasets under one group."""

    def __init__(self, f, group: str):
        h5py = _h5py()
        self.dsets = {}
        for name, dt in (
            ("xs", np.int16), ("ys", np.int16),
            ("ts", np.float64), ("ps", np.float64),
        ):
            self.dsets[name] = f.create_dataset(
                f"{group}/{name}", (0,), dtype=np.dtype(dt),
                maxshape=(None,), chunks=True,
            )

    def append(self, xs, ys, ts, ps) -> None:
        for name, data in zip(("xs", "ys", "ts", "ps"), (xs, ys, ts, ps)):
            d = self.dsets[name]
            n = len(data)
            d.resize(d.shape[0] + n, axis=0)
            if n:
                d[-n:] = data


def _package_image(f, group: str, image, timestamp: float, idx: int) -> None:
    image = np.asarray(image)
    d = f.create_dataset(
        f"{group}/image{idx:09d}", data=image, dtype=np.dtype(np.uint8)
    )
    d.attrs["size"] = image.shape
    d.attrs["timestamp"] = timestamp
    d.attrs["type"] = (
        "greyscale" if image.ndim == 2 or image.shape[-1] == 1 else "color_bgr"
    )


def _add_event_indices(f, ts_path: str, image_groups: Iterable[str]) -> None:
    """Attach ``event_idx`` (index of the event preceding each image's
    timestamp) to every image, as the reference does (``:75-92``)."""
    if ts_path not in f:
        return
    ts = f[ts_path][:]
    for group in image_groups:
        if group not in f:
            continue
        for name in f[group]:
            img = f[f"{group}/{name}"]
            idx = int(np.searchsorted(ts, img.attrs["timestamp"]))
            img.attrs["event_idx"] = max(0, idx - 1)


class H5Packager:
    """Single-stream recording writer (reference ``hdf5_packager``, ``:37-117``)."""

    def __init__(self, output_path: str):
        self.f = _h5py().File(output_path, "w")
        self.events = _EventGroup(self.f, "events")
        self._num_images = 0
        self._num_flow = 0

    def package_events(self, xs, ys, ts, ps) -> None:
        self.events.append(xs, ys, ts, ps)

    def package_image(self, image, timestamp: float, img_idx: Optional[int] = None) -> None:
        idx = self._num_images if img_idx is None else img_idx
        _package_image(self.f, "images", image, timestamp, idx)
        self._num_images += 1

    def package_flow(self, flow, timestamp: float, flow_idx: Optional[int] = None) -> None:
        idx = self._num_flow if flow_idx is None else flow_idx
        flow = np.asarray(flow, np.float32)
        d = self.f.create_dataset(f"flow/flow{idx:09d}", data=flow)
        d.attrs["size"] = flow.shape
        d.attrs["timestamp"] = timestamp
        self._num_flow += 1

    def add_metadata(
        self,
        num_pos: int,
        num_neg: int,
        t0: float,
        tk: float,
        sensor_size: Sequence[int],
    ) -> None:
        a = self.f.attrs
        a["num_events"] = num_pos + num_neg
        a["num_pos"] = num_pos
        a["num_neg"] = num_neg
        a["duration"] = tk - t0
        a["t0"] = t0
        a["tk"] = tk
        a["num_imgs"] = self._num_images
        a["num_flow"] = self._num_flow
        a["sensor_resolution"] = np.asarray(sensor_size, np.int32)
        _add_event_indices(self.f, "events/ts", ("images", "flow"))

    def close(self) -> None:
        self.f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class H5LadderPackager:
    """Multi-resolution recording writer — the training input format
    (reference ``hdf5_event_packager``, ``:119+``; read back by
    ``esr_tpu.data.records.H5Recording``)."""

    def __init__(self, output_path: str, rungs: Sequence[str] = DEFAULT_RUNGS):
        self.f = _h5py().File(output_path, "w")
        self.rungs = tuple(rungs)
        self.groups: Dict[str, _EventGroup] = {
            r: _EventGroup(self.f, f"{r}_events") for r in self.rungs
        }
        self._img_counts: Dict[str, int] = {}

    def package_events(self, rung: str, xs, ys, ts, ps) -> None:
        if rung not in self.groups:
            raise KeyError(f"unknown rung {rung!r}; have {self.rungs}")
        self.groups[rung].append(xs, ys, ts, ps)

    def package_image(self, rung: str, image, timestamp: float, img_idx: Optional[int] = None) -> None:
        idx = self._img_counts.get(rung, 0) if img_idx is None else img_idx
        _package_image(self.f, f"{rung}_images", image, timestamp, idx)
        self._img_counts[rung] = self._img_counts.get(rung, 0) + 1

    def add_metadata(self, sensor_size: Sequence[int]) -> None:
        self.f.attrs["sensor_resolution"] = np.asarray(sensor_size, np.int32)
        for r in self.rungs:
            _add_event_indices(
                self.f, f"{r}_events/ts", (f"{r}_images",)
            )

    def close(self) -> None:
        self.f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
