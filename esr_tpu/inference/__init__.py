"""Inference / evaluation harness."""

from esr_tpu.inference.harness import (
    InferenceRunner,
    aggregate_results,
    run_inference,
)

__all__ = ["InferenceRunner", "aggregate_results", "run_inference"]
