"""Inference / evaluation: sequential harness + batched streaming engine."""

from esr_tpu.inference.engine import StreamingEngine
from esr_tpu.inference.harness import (
    InferenceRunner,
    aggregate_results,
    run_inference,
)
from esr_tpu.inference.export import (
    export_checkpoint,
    load_exported_model,
    save_exported_model,
)

__all__ = [
    "InferenceRunner",
    "StreamingEngine",
    "aggregate_results",
    "run_inference",
    "export_checkpoint",
    "load_exported_model",
    "save_exported_model",
]
