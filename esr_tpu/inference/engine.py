"""Batched streaming inference: lane-packed recordings, scan-fused windows.

The sequential harness (``esr_tpu.inference.harness``) is the
reference-shaped loop: one python-dispatched forward per window per
recording at batch 1, plus a second per-window ``_metrics`` jit — exactly
the dispatch-bound regime the K-step fused training path (PR 2,
docs/PERF.md) eliminated on the training side. This module is the
inference counterpart:

- **lane packing** — ``B = lanes`` recordings stream concurrently, one per
  batch lane of a single ``(B, ...)`` forward, each lane carrying its own
  recurrent state. Lanes refill from the pending datalist at chunk
  boundaries (per-lane state reset on refill); a lane whose recording ends
  mid-chunk is zero-padded with a validity mask so masked windows
  contribute zero metric weight (``esr_tpu.data.loader.LanePackedChunks``
  owns the host-side scheduling contract).
- **scan fusion** — ``W = chunk_windows`` consecutive windows per lane run
  inside ONE device program: the chunk program reuses the production
  ``make_multi_step``/``lax.scan`` machinery from
  ``esr_tpu.training.multistep`` with the recurrent state in the donated
  scan carry, so the host pays one dispatch per ``B x W`` windows instead
  of one (plus a metrics jit) per window.
- **on-device metric accumulation** — per-window l1/mse/psnr/ssim (ESR and
  the bicubic baseline) are computed per lane inside the scanned program
  and accumulated into per-lane sums + valid-window counts riding the scan
  carry; the host reads back one small pytree per CHUNK instead of eight
  scalars per window. Per-window SSIM pairs additionally come back stacked
  (``(W, B)``) because the report's paired-delta diagnostics
  (``ssim_delta_*``, per-series stds — see the harness) are sample
  statistics the host computes with the same numpy code as the sequential
  path.
- **host/device overlap** — the chunk iterator feeds the existing
  ``DevicePrefetcher``: a producer thread rasterizes and stages chunk
  ``i+1`` while the device runs chunk ``i``, and chunk readbacks resolve
  one chunk behind dispatch (the same pending-deque idiom the sequential
  harness uses per window).

The engine is a drop-in producer for the report pipeline: per-recording
results carry the exact schema of ``InferenceRunner.run_recording`` (metric
means, ``time``/``params``, rmse at the aggregation boundary, window
diagnostics) and feed the same ``aggregate_results``/YAML writers, with
per-chunk ``infer_chunk`` telemetry spans (lanes, valid windows, windows/s)
replacing the sequential path's per-window ``infer_forward`` span
(docs/OBSERVABILITY.md, docs/INFERENCE.md).

Not supported in engine mode (use the sequential harness): LPIPS (needs
calibrated params and per-window host tensors) and per-window PNG dumps.
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from esr_tpu.analysis.retrace_guard import checked_jit
from esr_tpu.data.loader import DevicePrefetcher, LanePackedChunks
from esr_tpu.losses.restore import (
    l1_metric,
    mse_metric,
    psnr_metric,
    ssim_metric,
)
from esr_tpu.obs import active_sink, trace
from esr_tpu.ops.resize import interpolate

logger = logging.getLogger(__name__)

# per-lane sums accumulated on device, in the sequential tracker's key
# order (the per-recording result dict must carry the identical schema)
METRIC_KEYS = (
    "esr_l1", "esr_mse", "esr_ssim", "esr_psnr",
    "bicubic_l1", "bicubic_mse", "bicubic_ssim", "bicubic_psnr",
)

_METRIC_FNS = {
    "l1": l1_metric, "mse": mse_metric,
    "ssim": ssim_metric, "psnr": psnr_metric,
}


def make_chunk_fn(model, lanes: int, chunk_windows: int, kh: int, kw: int,
                  compute_dtype=None, precision=None):
    """Build the PURE fused-chunk program: ``(params, states, reset_keep,
    windows) -> (states, sums, stacked)``.

    One dispatch processes ``chunk_windows`` consecutive seqn-windows for
    each of ``lanes`` batch lanes: masked lane states are reset, the
    windows are scanned via the production ``make_multi_step`` machinery,
    and per-lane metric sums accumulate in the carry (module docstring).
    Returned UNJITTED so every consumer shares one definition:

    - :class:`StreamingEngine` wraps it in ``checked_jit`` with the
      recurrent-state carry donated (the traced path);
    - ``inference/export.py:export_chunk_program`` lowers it through
      ``jax.export`` into the AOT artifact the serving tier
      (``esr_tpu.serving``) loads so the serving process never traces;
    - the serving tier's per-request-class chunk sizing builds one program
      per distinct ``chunk_windows`` (docs/SERVING.md).

    ``(kh, kw)`` is the GT grid: the resize target is baked into the traced
    program, so a datalist at a new resolution needs a new program (shape
    changes alone would retrace, but a stale target would silently resize
    to the WRONG grid).

    ``compute_dtype`` is the precision rung (``esr_tpu.config.precision``)
    the checkpoint trained at: params/inputs/lane states are cast for the
    apply exactly like the train/eval steps, predictions are upcast to f32
    BEFORE the resize and metric math, and the per-lane metric sums stay
    f32 — so a bf16 chunk program reports through the identical metric
    pipeline. Callers must materialize the entry lane states in the same
    dtype (the donated carry's signature is part of the program).

    ``precision`` threads the RUNG itself for the paths a cast dtype
    cannot express: at ``"int8"`` (the PTQ serving rung,
    ``esr_tpu.config.quantize``) params/states/inputs stay f32
    (``compute_dtype`` must be ``None``) and the apply runs inside the
    int8 trace scope, so every contraction seam quantizes in-graph.
    The scope is entered INSIDE the traced body — retraces re-apply it.
    """
    from esr_tpu.config.precision import canonical_precision
    from esr_tpu.training.multistep import make_multi_step

    int8 = (precision is not None
            and canonical_precision(precision) == "int8")
    if int8 and compute_dtype is not None:
        raise ValueError(
            "precision='int8' quantizes at the seams — params/states stay "
            "f32, so compute_dtype must be None"
        )

    sum_keys = METRIC_KEYS + ("count",)

    def _to_gt_grid(imgs):
        if imgs.shape[1:3] != (kh, kw):
            return jax.vmap(
                lambda im: interpolate(im, (kh, kw), "bicubic")
            )(imgs)
        return imgs

    def run_chunk(params, states, reset_keep, windows):
        if compute_dtype is not None:
            params = jax.tree.map(
                lambda a: a.astype(compute_dtype), params
            )
            states = jax.tree.map(
                lambda z: z.astype(compute_dtype), states
            )

        def window_step(carry, win):
            states, sums = carry
            inp = win["inp_scaled"]
            if compute_dtype is not None:
                inp = inp.astype(compute_dtype)
            if int8:
                from esr_tpu.config.quantize import int8_scope

                with int8_scope():
                    pred, states = model.apply(params, inp, states)
            else:
                pred, states = model.apply(params, inp, states)
            pred = _to_gt_grid(pred.astype(jnp.float32))
            bicubic = _to_gt_grid(win["inp_mid"])
            per = {}
            for name, fn in _METRIC_FNS.items():
                vfn = jax.vmap(fn)
                per[f"esr_{name}"] = vfn(pred, win["gt"])
                per[f"bicubic_{name}"] = vfn(bicubic, win["gt"])
            valid = win["valid"]  # (B,) float mask
            # where, not multiply: a masked (zero-padded) window can
            # produce inf/nan metrics (e.g. psnr of a zero gt) and
            # inf * 0 would poison the sum with NaN
            sums = dict(sums)
            for k in METRIC_KEYS:
                sums[k] = sums[k] + jnp.where(valid > 0, per[k], 0.0)
            sums["count"] = sums["count"] + valid
            # per-window SSIM pairs stacked by the scan: the report's
            # paired-delta diagnostics are host-side sample statistics
            stacked = {
                "esr_ssim": per["esr_ssim"],
                "bicubic_ssim": per["bicubic_ssim"],
            }
            return (states, sums), stacked

        multi = make_multi_step(window_step, chunk_windows)
        # where, not multiply, for the same reason as the metric sums:
        # a lane state driven non-finite (overflow, padded-tail
        # garbage) must reset to a CLEAN zero, and 0 * inf is NaN
        states = jax.tree.map(
            lambda z: jnp.where(
                reset_keep.reshape((-1,) + (1,) * (z.ndim - 1)) > 0,
                z, 0.0,
            ),
            states,
        )
        sums0 = {
            k: jnp.zeros((lanes,), jnp.float32) for k in sum_keys
        }
        (states, sums), stacked = multi((states, sums0), windows)
        return states, sums, stacked

    return run_chunk


# -- per-lane recurrent-state save / restore ---------------------------------
# The serving tier's preemption contract (docs/SERVING.md): a stream evicted
# from its lane must resume BIT-IDENTICALLY later, possibly in a different
# lane or a different process. Extraction pulls one lane's slice of every
# state leaf to host numpy (float32 round-trips device -> numpy -> device
# bit-exactly); injection scatters it back into a lane slot. Both are
# host-side array ops OUTSIDE any trace — extraction blocks until the
# lane's last chunk resolved, which is exactly the barrier eviction needs.


def extract_lane_state(states, lane: int):
    """One lane's recurrent state -> host numpy pytree (bit-exact)."""
    return jax.tree.map(lambda z: np.asarray(z[lane]), states)


def inject_lane_state(states, lane: int, host_state):
    """Write a saved lane state (from :func:`extract_lane_state`) into lane
    ``lane`` of the batched device state; other lanes are untouched."""
    return jax.tree.map(
        lambda z, h: z.at[lane].set(jnp.asarray(h, z.dtype)),
        states, host_state,
    )


class StreamingEngine:
    """Lane-packed, scan-fused streaming inference over a datalist.

    One engine per trained model; ``run_datalist`` streams any number of
    recordings through ``lanes`` batch lanes in chunks of ``chunk_windows``
    fused windows. ``lanes=1, chunk_windows=1`` degenerates to the
    sequential harness's schedule (one window per dispatch, batch 1) and
    must produce the same metrics — pinned by ``tests/test_infer_engine.py``.
    """

    def __init__(
        self,
        model,
        params,
        seqn: int = 3,
        lanes: int = 4,
        chunk_windows: int = 8,
        prefetch_depth: int = 2,
        precision: Optional[str] = None,
    ):
        from esr_tpu.config.precision import (
            compute_dtype_of,
            resolve_precision,
        )

        if lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        if chunk_windows < 1:
            raise ValueError(
                f"chunk_windows must be >= 1, got {chunk_windows}"
            )
        self.model = model
        self.params = params
        self.seqn = int(seqn)
        self.lanes = int(lanes)
        self.chunk_windows = int(chunk_windows)
        self.prefetch_depth = int(prefetch_depth)
        # the rung the caller resolved (CLI > checkpoint config > f32,
        # esr_tpu.config.precision); `None` means f32 — the engine never
        # guesses, the harness/serving entrypoints own the resolution
        self.precision = resolve_precision(cli=precision)
        self._compute_dtype = compute_dtype_of(self.precision)
        # chunk program cache, keyed by GT resolution: the resize target is
        # baked into the traced program, so a datalist at a new resolution
        # must rebuild (shape changes alone would retrace, but a stale
        # (kh, kw) would silently resize to the WRONG grid)
        self._run_chunk = None
        self._chunk_key = None

    # -- fused chunk program ------------------------------------------------

    def _build_chunk_fn(self, kh: int, kw: int):
        """The one-dispatch-per-chunk executable: the shared
        :func:`make_chunk_fn` program under ``checked_jit``, with the
        recurrent-state carry donated so lane states keep single-copy HBM
        residency across chunks exactly like the training carry."""
        return checked_jit(
            make_chunk_fn(self.model, self.lanes, self.chunk_windows,
                          kh, kw, compute_dtype=self._compute_dtype,
                          precision=self.precision),
            donate_argnums=(1,), name="infer_engine_chunk",
        )

    # -- host loop ----------------------------------------------------------

    @staticmethod
    def _stage(chunk: Dict) -> Dict:
        """Host chunk -> device arrays (runs on the prefetcher thread, so
        the upload overlaps the previous chunk's device compute)."""
        return {
            "windows": {
                k: jnp.asarray(v) for k, v in chunk["windows"].items()
            },
            "reset_keep": jnp.asarray(chunk["reset_keep"]),
        }

    def run_datalist(
        self,
        data_list: Sequence[str],
        dataset_config: Dict,
    ) -> Tuple[List[Dict[str, float]], List[str]]:
        """Stream every recording of ``data_list``; returns per-recording
        result dicts (sequential-harness schema) in datalist order plus the
        recording names — ready for ``aggregate_results``."""
        from esr_tpu.inference.harness import (
            _attach_rmse,
            _attach_ssim_window_stats,
            _num_params,
        )

        chunks = LanePackedChunks(
            data_list, dataset_config,
            lanes=self.lanes, chunk_windows=self.chunk_windows,
        )
        kh, kw = chunks.gt_resolution
        if self._run_chunk is None or self._chunk_key != (kh, kw):
            self._run_chunk = self._build_chunk_fn(kh, kw)
            self._chunk_key = (kh, kw)

        acc: Dict[str, Dict] = {}
        for path in data_list:
            acc[path] = {
                "sums": {k: 0.0 for k in METRIC_KEYS},
                "count": 0,
                "time_s": 0.0,
                "ssim": {"esr_ssim": [], "bicubic_ssim": []},
            }

        sink = active_sink()
        params_m = _num_params(self.params)
        # init_states aliases one zeros buffer across slots; the donated
        # carry needs every leaf distinct (donating one buffer twice is an
        # XLA error), so materialize each leaf as its own array
        states = jax.tree.map(
            jnp.array, self.model.init_states(self.lanes, kh, kw)
        )
        if self._compute_dtype is not None:
            # the donated carry's dtype is part of the program signature:
            # materialize lane states at the compute width so chunk 0
            # traces the same program every later chunk reuses (an f32
            # entry would retrace once and break donation aliasing)
            states = jax.tree.map(
                lambda z: z.astype(self._compute_dtype), states
            )

        def _resolve(entry) -> None:
            """Read back one chunk's device outputs and fold them into the
            per-recording accumulators (blocks until the chunk is done)."""
            idx, meta, sums_dev, stacked_dev, t_dispatch = entry
            sums = {k: np.asarray(v) for k, v in sums_dev.items()}
            stacked = {k: np.asarray(v) for k, v in stacked_dev.items()}
            t_res = time.monotonic()
            seconds = t_res - t_dispatch
            total_valid = int(round(float(sums["count"].sum())))
            for lane, m in enumerate(meta):
                if m is None or m["windows"] == 0:
                    continue
                a = acc[m["path"]]
                for k in METRIC_KEYS:
                    a["sums"][k] += float(sums[k][lane])
                a["count"] += m["windows"]
                # the chunk's wall-clock, amortized over its valid windows
                a["time_s"] += seconds * m["windows"] / total_valid
                for k in ("esr_ssim", "bicubic_ssim"):
                    a["ssim"][k].extend(
                        float(v) for v in stacked[k][: m["windows"], lane]
                    )
            if sink is not None:
                # v2: the chunk span carries identity + clock edges
                # (dispatch -> readback on the sink's t axis) and names
                # the recordings bound to each lane, so the exporter can
                # draw what each lane was serving; the ambient infer_run
                # context supplies trace_id/parent via the sink
                sink.span(
                    "infer_chunk", seconds,
                    span_id=trace.new_id(),
                    begin=round(sink.rel(t_dispatch), 6),
                    end=round(sink.rel(t_res), 6),
                    chunk=idx, lanes=self.lanes,
                    chunk_windows=self.chunk_windows,
                    windows=total_valid,
                    recordings=[
                        os.path.basename(m["path"]) if m else None
                        for m in meta
                    ],
                    windows_per_sec=round(total_valid / seconds, 3)
                    if seconds > 0 else None,
                )

        pending: deque = deque()
        # one trace per engine pass (schema v2): chunk spans, prefetcher
        # health, and compile events all auto-link under this root — the
        # offline twin of the serving tier's per-request traces
        with trace.span(
            "infer_run", recordings=len(data_list), lanes=self.lanes,
            chunk_windows=self.chunk_windows,
        ):
            with DevicePrefetcher(
                chunks, self._stage, depth=self.prefetch_depth
            ) as pf:
                for idx, (host_chunk, staged) in enumerate(pf):
                    t0 = time.monotonic()
                    states, sums, stacked = self._run_chunk(
                        self.params, states,
                        staged["reset_keep"], staged["windows"],
                    )
                    pending.append(
                        (idx, host_chunk["meta"], sums, stacked, t0)
                    )
                    # resolve one chunk BEHIND dispatch so the readback of
                    # chunk i overlaps the device running chunk i+1
                    if len(pending) > 1:
                        _resolve(pending.popleft())
            while pending:
                _resolve(pending.popleft())

        results, names = [], []
        for path in data_list:
            a = acc[path]
            n = a["count"]
            if n == 0:
                # mirror the sequential tracker's zero-count behavior
                # (avg of no updates reports 0.0) so results stay aligned
                # with the datalist even for a windowless recording
                logger.warning("recording %s produced no windows", path)
            result = {
                k: (a["sums"][k] / n if n else 0.0) for k in METRIC_KEYS
            }
            result["time"] = a["time_s"] / n if n else 0.0
            result["params"] = params_m
            _attach_rmse(result)
            _attach_ssim_window_stats(result, a["ssim"])
            results.append(result)
            names.append(os.path.basename(path))
        return results, names
