"""Model export: serialized, ahead-of-time-lowered forward functions.

The reference ships an ONNX/TensorRT deployment variant of its hot op
(``models/DCNv2/dcn_v2_onnx.py`` — a ``symbolic()`` hook emitting a TensorRT
"Plugin" node). The TPU-native equivalent of that deployment path is
``jax.export``: the jitted forward — recurrent state threading, Pallas DCN
kernel and all — is lowered once to StableHLO and serialized to a
self-contained artifact that any later jax (or pure-XLA) runtime can load and
run without the model source. Unlike the reference's per-op plugin, the WHOLE
program is exported, so there is nothing to re-register on the consumer side.

Artifact layout (a single ``.npz``-style zip is deliberately avoided — the
serialized module is opaque bytes + a small JSON sidecar):

- ``<path>`` — ``jax.export`` serialization of
  ``fn(params, x, states) -> (y, states)``;
- ``<path>.json`` — model name/config, input/state tree structure and shapes,
  so consumers can build feeds without importing this package.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.export  # noqa: F401 - jax does not auto-import the submodule
import jax.numpy as jnp
import numpy as np


def _shape_dtype(tree):
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), jnp.asarray(a).dtype), tree
    )


def export_forward(
    model,
    params,
    example_input: Any,
    example_states: Any,
    platforms: Tuple[str, ...] = ("tpu", "cpu"),
) -> bytes:
    """Lower ``model.apply(params, x, states) -> (y, states)`` and serialize.

    ``platforms`` lists the lowering targets baked into the artifact; the
    default covers the TPU serving path plus a CPU fallback so the artifact
    loads anywhere. A multi-platform artifact must lower every op for every
    target, which the TPU-only Pallas DCN kernel cannot — models exposing a
    ``dcn_impl`` knob are transparently rebound to the portable jnp
    formulation (identical math; the kernel is a speed/precision upgrade,
    ``ops/dcn.py:142-148``). Export with ``platforms=('tpu',)`` to keep the
    fused kernel in the artifact.
    """
    if len(platforms) > 1 and getattr(model, "dcn_impl", None) in ("auto", "pallas"):
        model = model.clone(dcn_impl="jnp")

    def fn(params, x, states):
        return model.apply(params, x, states)

    exported = jax.export.export(jax.jit(fn), platforms=list(platforms))(
        _shape_dtype(params), _shape_dtype(example_input),
        _shape_dtype(example_states),
    )
    return bytes(exported.serialize())


def load_exported(data: bytes) -> Callable:
    """Deserialize an :func:`export_forward` artifact into a callable with
    the original ``(params, x, states) -> (y, states)`` signature."""
    return jax.export.deserialize(data).call


def save_exported_model(
    path: str,
    model,
    params,
    example_input: Any,
    example_states: Any,
    config: Optional[Dict] = None,
    platforms: Tuple[str, ...] = ("tpu", "cpu"),
) -> str:
    """Serialize to ``path`` (+ ``path.json`` sidecar). Returns ``path``."""
    blob = export_forward(model, params, example_input, example_states, platforms)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(blob)

    def describe(tree):
        leaves, treedef = jax.tree.flatten(tree)
        return {
            "treedef": str(treedef),
            "shapes": [list(np.shape(l)) for l in leaves],
            "dtypes": [str(jnp.asarray(l).dtype) for l in leaves],
        }

    sidecar = {
        "model": type(model).__name__,
        "config": config or {},
        "platforms": list(platforms),
        "input": describe(example_input),
        "states": describe(example_states),
    }
    with open(path + ".json", "w") as f:
        json.dump(sidecar, f, indent=2)
    return path


def load_exported_model(path: str) -> Tuple[Callable, Dict]:
    """Load ``(callable, sidecar_dict)`` back from :func:`save_exported_model`."""
    with open(path, "rb") as f:
        fn = load_exported(f.read())
    sidecar: Dict = {}
    if os.path.exists(path + ".json"):
        with open(path + ".json") as f:
            sidecar = json.load(f)
    return fn, sidecar


def export_checkpoint(ckpt_path: str, out_path: str,
                      batch: int = 1, height: int = 64, width: int = 64) -> str:
    """Checkpoint directory -> deployable artifact: rebuilds the model from
    the embedded config (the same convention inference uses,
    ``training/checkpoint.py:load_for_inference``) and exports its forward
    at the given input geometry."""
    from esr_tpu.training.checkpoint import load_for_inference

    model, params, config = load_for_inference(ckpt_path)
    seqn = int(config.get("model", {}).get("args", {}).get("num_frame", 3))
    inch = int(getattr(model, "inch", 2))
    x = jnp.zeros((batch, seqn, height, width, inch), jnp.float32)
    states = model.init_states(batch, height, width)
    return save_exported_model(
        out_path, model, params, x, states, config=config
    )
