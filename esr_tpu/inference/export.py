"""Model export: serialized, ahead-of-time-lowered forward functions.

The reference ships an ONNX/TensorRT deployment variant of its hot op
(``models/DCNv2/dcn_v2_onnx.py`` — a ``symbolic()`` hook emitting a TensorRT
"Plugin" node). The TPU-native equivalent of that deployment path is
``jax.export``: the jitted forward — recurrent state threading, Pallas DCN
kernel and all — is lowered once to StableHLO and serialized to a
self-contained artifact that any later jax (or pure-XLA) runtime can load and
run without the model source. Unlike the reference's per-op plugin, the WHOLE
program is exported, so there is nothing to re-register on the consumer side.

Artifact layout (a single ``.npz``-style zip is deliberately avoided — the
serialized module is opaque bytes + a small JSON sidecar):

- ``<path>`` — ``jax.export`` serialization of
  ``fn(params, x, states) -> (y, states)``;
- ``<path>.json`` — model name/config, input/state tree structure and shapes,
  so consumers can build feeds without importing this package.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.export  # noqa: F401 - jax does not auto-import the submodule
import jax.numpy as jnp
import numpy as np


def _shape_dtype(tree):
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), jnp.asarray(a).dtype), tree
    )


def _portable_dcn(model, platforms: Tuple[str, ...]):
    """Rebind TPU-only Pallas DCN dispatch to the portable jnp formulation
    for multi-platform artifacts (identical math; the kernels are a
    speed/precision upgrade). Both direction knobs are neutralized:
    ``dcn_impl`` (train direction) and ``dcn_impl_fwd`` (the
    forward/serving direction added in ops/dcn.py's direction-aware
    dispatch) — an exported chunk program runs train=False, so a leaked
    ``dcn_impl_fwd='pallas'`` would otherwise bake the unlowerable kernel
    into the CPU target."""
    if len(platforms) <= 1:
        return model
    updates = {}
    if getattr(model, "dcn_impl", None) in ("auto", "pallas"):
        updates["dcn_impl"] = "jnp"
    if getattr(model, "dcn_impl_fwd", None) in ("auto", "pallas"):
        updates["dcn_impl_fwd"] = "jnp"
    # activity predication is a Pallas-only feature; on the jnp
    # formulation it is already a no-op, but neutralize it anyway so the
    # portable artifact's model config reads dense
    if getattr(model, "dcn_sparse", False):
        updates["dcn_sparse"] = False
    return model.clone(**updates) if updates else model


def export_forward(
    model,
    params,
    example_input: Any,
    example_states: Any,
    platforms: Tuple[str, ...] = ("tpu", "cpu"),
) -> bytes:
    """Lower ``model.apply(params, x, states) -> (y, states)`` and serialize.

    ``platforms`` lists the lowering targets baked into the artifact; the
    default covers the TPU serving path plus a CPU fallback so the artifact
    loads anywhere. A multi-platform artifact must lower every op for every
    target, which the TPU-only Pallas DCN kernel cannot — models exposing a
    ``dcn_impl`` knob are transparently rebound to the portable jnp
    formulation (identical math; the kernel is a speed/precision upgrade,
    ``ops/dcn.py:142-148``). Export with ``platforms=('tpu',)`` to keep the
    fused kernel in the artifact.
    """
    model = _portable_dcn(model, platforms)

    def fn(params, x, states):
        return model.apply(params, x, states)

    exported = jax.export.export(jax.jit(fn), platforms=list(platforms))(
        _shape_dtype(params), _shape_dtype(example_input),
        _shape_dtype(example_states),
    )
    return bytes(exported.serialize())


def load_exported(data: bytes) -> Callable:
    """Deserialize an :func:`export_forward` artifact into a callable with
    the original ``(params, x, states) -> (y, states)`` signature."""
    return jax.export.deserialize(data).call


def save_exported_model(
    path: str,
    model,
    params,
    example_input: Any,
    example_states: Any,
    config: Optional[Dict] = None,
    platforms: Tuple[str, ...] = ("tpu", "cpu"),
) -> str:
    """Serialize to ``path`` (+ ``path.json`` sidecar). Returns ``path``."""
    blob = export_forward(model, params, example_input, example_states, platforms)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(blob)

    def describe(tree):
        leaves, treedef = jax.tree.flatten(tree)
        return {
            "treedef": str(treedef),
            "shapes": [list(np.shape(l)) for l in leaves],
            "dtypes": [str(jnp.asarray(l).dtype) for l in leaves],
        }

    sidecar = {
        "model": type(model).__name__,
        "config": config or {},
        "platforms": list(platforms),
        "input": describe(example_input),
        "states": describe(example_states),
    }
    with open(path + ".json", "w") as f:
        json.dump(sidecar, f, indent=2)
    return path


def load_exported_model(path: str) -> Tuple[Callable, Dict]:
    """Load ``(callable, sidecar_dict)`` back from :func:`save_exported_model`."""
    with open(path, "rb") as f:
        fn = load_exported(f.read())
    sidecar: Dict = {}
    if os.path.exists(path + ".json"):
        with open(path + ".json") as f:
            sidecar = json.load(f)
    return fn, sidecar


def export_chunk_program(
    model,
    params,
    lanes: int,
    chunk_windows: int,
    gt_hw: Tuple[int, int],
    inp_hw: Optional[Tuple[int, int]] = None,
    lr_hw: Optional[Tuple[int, int]] = None,
    seqn: int = 3,
    platforms: Tuple[str, ...] = ("tpu", "cpu"),
    precision: Optional[str] = None,
) -> bytes:
    """Lower the ENGINE CHUNK PROGRAM (``inference/engine.make_chunk_fn``)
    and serialize — the AOT artifact the serving tier loads so the serving
    process never traces (``esr_tpu.serving.server``, docs/SERVING.md).

    Signature of the exported callable: ``(params, states, reset_keep,
    windows) -> (states, sums, stacked)`` with ``windows`` the engine's
    ``{"inp_scaled": (W, B, seqn, ih, iw, c), "gt": (W, B, kh, kw, c),
    "inp_mid": (W, B, lh, lw, c), "valid": (W, B)}`` chunk dict. ``gt_hw``
    is the GT grid (also the recurrent-state grid); ``inp_hw`` defaults to
    the GT grid (LR events are rasterized onto it upstream) and ``lr_hw``
    to the LR sensor grid implied by nothing — pass it explicitly for a
    non-trivial scale. Multi-platform exports rebind the TPU-only Pallas
    DCN kernel to the portable jnp formulation, as in
    :func:`export_forward`.
    """
    from esr_tpu.config.precision import (
        compute_dtype_of,
        resolve_precision,
    )
    from esr_tpu.inference.engine import make_chunk_fn

    model = _portable_dcn(model, platforms)
    kh, kw = gt_hw
    ih, iw = inp_hw if inp_hw is not None else gt_hw
    lh, lw = lr_hw if lr_hw is not None else gt_hw
    inch = int(getattr(model, "inch", 2))
    w_, b = int(chunk_windows), int(lanes)
    windows = {
        "inp_scaled": jnp.zeros((w_, b, seqn, ih, iw, inch), jnp.float32),
        "gt": jnp.zeros((w_, b, kh, kw, inch), jnp.float32),
        "inp_mid": jnp.zeros((w_, b, lh, lw, inch), jnp.float32),
        "valid": jnp.zeros((w_, b), jnp.float32),
    }
    rung = resolve_precision(cli=precision)
    compute_dtype = compute_dtype_of(rung)
    states = model.init_states(b, kh, kw)
    if compute_dtype is not None:
        # the donated carry's dtype is part of the exported signature —
        # it must match what the serving tier materializes at this rung
        states = jax.tree.map(
            lambda z: jnp.asarray(z, compute_dtype), states
        )
    reset_keep = jnp.zeros((b,), jnp.float32)
    # int8 bakes the QUANTIZED program (seams quantize in-graph; states
    # stay f32) — the sidecar's rung + bind-time refusal cover it like bf16
    fn = make_chunk_fn(model, b, w_, kh, kw, compute_dtype=compute_dtype,
                       precision=rung)
    exported = jax.export.export(jax.jit(fn), platforms=list(platforms))(
        _shape_dtype(params), _shape_dtype(states),
        _shape_dtype(reset_keep), _shape_dtype(windows),
    )
    return bytes(exported.serialize())


def export_checkpoint(ckpt_path: str, out_path: str,
                      batch: int = 1, height: int = 64, width: int = 64,
                      program: str = "forward",
                      chunk_windows: int = 8, scale: int = 2,
                      platforms: Tuple[str, ...] = ("tpu", "cpu"),
                      precision: Optional[str] = None) -> str:
    """Checkpoint directory -> deployable artifact: rebuilds the model from
    the embedded config (the same convention inference uses,
    ``training/checkpoint.py:load_for_inference``) and exports at the given
    input geometry.

    ``program`` selects WHAT is lowered:

    - ``"forward"`` (default): one ``model.apply`` call at batch ``batch``
      — the single-stream deployment artifact;
    - ``"engine_chunk"``: the fused chunk program at ``batch`` lanes x
      ``chunk_windows`` scan-fused windows on a ``(height, width)`` GT
      grid with an LR grid of ``(height//scale, width//scale)`` — the
      serving tier's AOT artifact (one per request-class
      ``chunk_windows``; ``esr_tpu.serving``, docs/SERVING.md).

    The sidecar records ``program`` plus, for chunk programs, the
    ``lanes``/``chunk_windows`` geometry the serving loader validates
    against its configuration.
    """
    if program not in ("forward", "engine_chunk"):
        raise ValueError(
            f"unknown program {program!r} (forward | engine_chunk)"
        )
    from esr_tpu.training.checkpoint import load_for_inference

    from esr_tpu.config.precision import resolve_precision

    model, params, config = load_for_inference(ckpt_path)
    seqn = int(config.get("model", {}).get("args", {}).get("num_frame", 3))
    inch = int(getattr(model, "inch", 2))
    # same one-policy resolution as infer/serve: explicit argument >
    # checkpoint trainer.precision > f32; the sidecar records the rung
    # and the serving loader refuses a mismatched one
    precision = resolve_precision(
        cli=precision,
        config=(config.get("trainer") or {}).get("precision"),
    )
    if program == "engine_chunk":
        blob = export_chunk_program(
            model, params, lanes=batch, chunk_windows=chunk_windows,
            gt_hw=(height, width),
            lr_hw=(height // scale, width // scale),
            seqn=seqn, platforms=platforms, precision=precision,
        )
        os.makedirs(os.path.dirname(os.path.abspath(out_path)),
                    exist_ok=True)
        with open(out_path, "wb") as f:
            f.write(blob)
        sidecar = {
            "model": type(model).__name__,
            "program": "engine_chunk",
            "config": config,
            "platforms": list(platforms),
            "lanes": int(batch),
            "chunk_windows": int(chunk_windows),
            "gt_hw": [height, width],
            "lr_hw": [height // scale, width // scale],
            "seqn": seqn,
            "precision": precision,
        }
        with open(out_path + ".json", "w") as f:
            json.dump(sidecar, f, indent=2, default=str)
        return out_path
    x = jnp.zeros((batch, seqn, height, width, inch), jnp.float32)
    states = model.init_states(batch, height, width)
    return save_exported_model(
        out_path, model, params, x, states, config=config,
        platforms=platforms,
    )
