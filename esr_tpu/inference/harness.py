"""Streaming inference over recordings: ESR vs bicubic metrics + reports.

Rebuilds ``infer_ours_cnt.py`` (reference ``:22-115`` per-recording body,
``:160-350`` driver):

- one :class:`InferenceRunner` per trained model: the forward is jit'd once
  and reused across recordings;
- recurrent state is reset ONCE per recording and persists across the whole
  stream (reference ``:54`` — train resets per batch, inference per
  recording);
- each length-L sequence contributes its FIRST seqn-window
  (``inputs_seq[0]``, reference ``:55-56``), sequences are non-overlapping
  (step_size = L by default), batch 1, in order;
- metrics per window: esr_{l1,mse,ssim,psnr[,lpips]} against the GT count
  image of the middle frame, and the same for the bicubic-upsampled LR input
  (the classical baseline, reference ``:78,86-100``); per-recording means via
  :class:`MetricTracker`; datalist-level breakdown + means
  (reference ``:336-347``);
- LPIPS only runs when calibrated params are supplied — the random-backbone
  fallback must be requested explicitly upstream
  (``load_lpips_params(allow_uncalibrated=True)``);
- optional PNG dumps in the reference's directory layout (``:44-49,104-109``);
- per-forward latency (timed around ``block_until_ready``) and params count
  (reference ``:65-67,71-74``); when a process-active telemetry sink exists
  (``esr_tpu.obs``, docs/OBSERVABILITY.md) each sequence's forward latency
  is also emitted as an ``infer_forward`` span tagged with the recording
  and window index, so tail latency is a queryable series rather than one
  averaged number in the YAML report.
"""

from __future__ import annotations

import logging
import os
import time
from collections import defaultdict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from esr_tpu.analysis.retrace_guard import checked_jit
from esr_tpu.data.loader import InferenceSequenceLoader
from esr_tpu.obs import active_sink
from esr_tpu.losses.restore import (
    l1_metric,
    mse_metric,
    psnr_metric,
    ssim_metric,
)
from esr_tpu.ops.resize import interpolate
from esr_tpu.utils.trackers import MetricTracker, YamlLogger
from esr_tpu.utils.vis_events import render_event_cnt, render_frame, save_image

logger = logging.getLogger(__name__)

_IMG_DIRS = (
    "lr_event_img",
    "hr_scaled_event_img",
    "hr_esr_event_img",
    "hr_bicubic_event_img",
    "hr_gt_event_img",
)


def _num_params(params) -> float:
    return sum(np.asarray(p).size for p in jax.tree.leaves(params)) / 1e6


class InferenceRunner:
    def __init__(
        self,
        model,
        params,
        seqn: int = 3,
        lpips_model=None,
        lpips_params=None,
        precision: Optional[str] = None,
    ):
        from esr_tpu.config.precision import (
            compute_dtype_of,
            resolve_precision,
        )

        self.model = model
        self.params = params
        self.seqn = seqn
        self.mid_idx = (seqn - 1) // 2
        # one precision policy (esr_tpu.config.precision): the caller
        # resolves CLI > checkpoint config > f32 and passes the rung; the
        # runner casts its params copy once so every recording's forward
        # runs the width the checkpoint trained at. Metrics stay f32 (the
        # prediction is upcast before the metric jit).
        self.precision = resolve_precision(cli=precision)
        self._compute_dtype = compute_dtype_of(self.precision)
        if self._compute_dtype is not None:
            self.params = jax.tree.map(
                lambda a: jnp.asarray(a).astype(self._compute_dtype),
                params,
            )

        # checked_jit (docs/ANALYSIS.md): inference retraces now surface as
        # `compile` telemetry events exactly like the training jits'. The
        # budget is above the default because one runner legitimately spans
        # a multi-resolution datalist (one retrace per distinct shape).
        # int8 rung: params/states/inputs stay f32 (compute_dtype is None);
        # the scope is entered INSIDE the traced body so every retrace
        # re-applies the seam quantization (esr_tpu.config.quantize).
        if self.precision == "int8":
            from esr_tpu.config.quantize import int8_scope

            def _fwd_int8(params, x, states):
                with int8_scope():
                    return model.apply(params, x, states)

            self._fwd = checked_jit(
                _fwd_int8, name="infer_fwd", max_traces=16
            )
        else:
            self._fwd = checked_jit(
                model.apply, name="infer_fwd", max_traces=16
            )

        self.lpips = None
        if lpips_model is not None and lpips_params is not None:
            self.lpips = checked_jit(
                lambda a, b: lpips_model.multi_channel(lpips_params, a, b),
                name="infer_lpips", max_traces=16,
            )

        @checked_jit(name="infer_metrics", max_traces=16)
        def _metrics(pred, base, gt):
            return {
                "esr_l1": l1_metric(pred, gt),
                "esr_mse": mse_metric(pred, gt),
                "esr_ssim": ssim_metric(pred, gt),
                "esr_psnr": psnr_metric(pred, gt),
                "bicubic_l1": l1_metric(base, gt),
                "bicubic_mse": mse_metric(base, gt),
                "bicubic_ssim": ssim_metric(base, gt),
                "bicubic_psnr": psnr_metric(base, gt),
            }

        self._metrics = _metrics

    def run_recording(
        self,
        data_path: str,
        dataset_config: Dict,
        out_dir: Optional[str] = None,
        save_images: bool = False,
        report: bool = True,
    ) -> Dict[str, float]:
        """Stream one recording; returns the per-recording metric means."""
        loader = InferenceSequenceLoader(data_path, dataset_config)
        kh, kw = loader.gt_resolution

        keys = ["esr_l1", "esr_mse", "esr_ssim", "esr_psnr",
                "bicubic_l1", "bicubic_mse", "bicubic_ssim", "bicubic_psnr",
                "time", "params"]
        if self.lpips is not None:
            keys += ["esr_lpips", "bicubic_lpips"]
        # sink=False: this tracker is a local aggregator for the YAML
        # report — with the default active-sink fallback every per-window
        # metric (incl. latency) would double into the telemetry stream
        # next to the authoritative infer_forward spans below
        track = MetricTracker(keys, sink=False)
        track.update("params", _num_params(self.params))

        img_root = None
        if save_images and out_dir is not None:
            img_root = os.path.join(out_dir, "event_img")
            for d in _IMG_DIRS:
                os.makedirs(os.path.join(img_root, d), exist_ok=True)
            os.makedirs(os.path.join(out_dir, "img", "gt_img"), exist_ok=True)

        # state persists across the WHOLE recording (reference :54)
        states = self.model.init_states(1, kh, kw)
        if self._compute_dtype is not None:
            states = jax.tree.map(
                lambda z: z.astype(self._compute_dtype), states
            )

        # per-window SSIM samples: count maps are sparse enough that the
        # ESR-vs-bicubic SSIM gap can sit inside the sampling noise
        # (r4 2x demo). The two series are PAIRED per window (same GT, same
        # content), so the testable noise-floor statistic is the paired
        # difference — its mean/std/sign-count — not the per-series stds
        # (shared content variance dominates those but cancels in the
        # delta); per-series stds are kept as descriptive context only.
        ssim_samples = {"esr_ssim": [], "bicubic_ssim": []}
        sink = active_sink()
        rec_name = os.path.basename(data_path)

        # Deferred metric readback: `float()`-ing the `_metrics` dict the
        # moment it is dispatched serializes a device->host sync into every
        # window. Instead the dispatched (still-device) scalars ride a
        # 1-deep pending deque and resolve while the NEXT window's forward
        # runs — same values, same order, one window of readback latency
        # hidden behind device compute.
        pending: "deque" = deque()

        def _resolve(entry) -> None:
            metrics, lpips_pair = entry
            for k, v in metrics.items():
                track.update(k, float(v))
                if k in ssim_samples:
                    ssim_samples[k].append(float(v))
            if lpips_pair is not None:
                track.update("esr_lpips", float(lpips_pair[0]))
                track.update("bicubic_lpips", float(lpips_pair[1]))

        for i, batch in enumerate(loader):
            window = {
                k: v[:, : self.seqn] for k, v in batch.items()
            }  # inputs_seq[0]
            inp_scaled = jnp.asarray(window["inp_scaled_cnt"])
            if self._compute_dtype is not None:
                inp_scaled = inp_scaled.astype(self._compute_dtype)

            t0 = time.perf_counter()
            pred, states = self._fwd(self.params, inp_scaled, states)
            if self._compute_dtype is not None:
                # metrics/PNG dumps consume f32 exactly like the f32 path
                pred = pred.astype(jnp.float32)
            # intentional per-window latency probe (the one sequential-mode
            # sync the deferred-readback audit keeps): bounding the forward
            # here is what makes `time`/`infer_forward` true dispatch->ready
            # wall per window
            pred = jax.block_until_ready(pred)
            latency = time.perf_counter() - t0
            track.update("time", latency)
            if sink is not None:
                # per-sequence latency span: block_until_ready bounds the
                # forward, so this is true dispatch->ready wall per window
                sink.span(
                    "infer_forward", latency, recording=rec_name, window=i
                )

            gt = jnp.asarray(window["gt_cnt"][0, self.mid_idx])  # [kH,kW,2]
            inp_cnt = jnp.asarray(window["inp_cnt"][0, self.mid_idx])
            pred0 = pred[0]
            if pred0.shape[:2] != (kh, kw):
                pred0 = interpolate(pred0, (kh, kw), "bicubic")
            bicubic = interpolate(inp_cnt, (kh, kw), "bicubic")

            lpips_pair = None
            if self.lpips is not None:
                lpips_pair = (self.lpips(pred0, gt), self.lpips(bicubic, gt))
            pending.append((self._metrics(pred0, bicubic, gt), lpips_pair))
            if len(pending) > 1:
                _resolve(pending.popleft())

            if img_root is not None:
                pred_np = np.asarray(pred0)
                views = {
                    "lr_event_img": np.asarray(inp_cnt),
                    "hr_scaled_event_img": window["inp_scaled_cnt"][0, self.mid_idx],
                    "hr_esr_event_img": np.round(pred_np),
                    "hr_bicubic_event_img": np.asarray(bicubic),
                    "hr_gt_event_img": np.asarray(gt),
                }
                for d, img in views.items():
                    save_image(
                        os.path.join(img_root, d, f"{i:09d}.png"),
                        render_event_cnt(img),
                    )
                if "gt_img" in window:
                    save_image(
                        os.path.join(out_dir, "img", "gt_img", f"{i:09d}.png"),
                        render_frame(window["gt_img"][0, self.mid_idx]),
                    )

        while pending:
            _resolve(pending.popleft())

        result = track.result()
        _attach_rmse(result)
        _attach_ssim_window_stats(result, ssim_samples)
        if report and out_dir is not None:
            _write_recording_report(out_dir, data_path, dataset_config, result)
        return result


def _attach_rmse(metrics: Dict[str, float]) -> None:
    """Derive rmse = sqrt(aggregated mse) IN PLACE at an aggregation
    boundary. The BASELINE.md north star is stated in RMSE but the
    reference reports only per-window-averaged MSE
    (``infer_ours_cnt.py:336-347``), so the comparable RMSE is the sqrt
    of the aggregated MSE — NOT a mean of per-window sqrts, which
    Jensen's inequality biases low whenever per-window MSE varies."""
    for side in ("esr", "bicubic"):
        if f"{side}_mse" in metrics:
            metrics[f"{side}_rmse"] = float(np.sqrt(metrics[f"{side}_mse"]))


def _attach_ssim_window_stats(
    result: Dict[str, float], ssim_samples: Dict[str, List[float]]
) -> None:
    """Window-count + paired-SSIM-delta diagnostics IN PLACE from the
    per-window SSIM samples (see the pairing rationale in
    :meth:`InferenceRunner.run_recording`). Shared by the sequential
    harness and the batched engine so both report byte-identical schema
    computed by the same numpy code."""
    n_win = len(ssim_samples["esr_ssim"])
    result["n_windows"] = float(n_win)
    if n_win:
        delta = (np.asarray(ssim_samples["esr_ssim"])
                 - np.asarray(ssim_samples["bicubic_ssim"]))
        result["ssim_delta_mean"] = float(delta.mean())
        result["ssim_delta_pos_frac"] = float((delta > 0).mean())
        if n_win > 1:
            result["ssim_delta_std"] = float(delta.std(ddof=1))
            for k, vals in ssim_samples.items():
                result[f"{k}_std"] = float(np.std(vals, ddof=1))


def _write_recording_report(
    out_dir: str, data_path: str, dataset_config: Dict, result: Dict
) -> None:
    """The per-recording ``inference.yml`` — one writer for both inference
    modes, so the engine's reports stay byte-identical in schema."""
    os.makedirs(out_dir, exist_ok=True)
    with YamlLogger(os.path.join(out_dir, "inference.yml")) as yl:
        yl.log_info(f"inference on {data_path}")
        yl.log_dict(dataset_config, "eval_dataset_config")
        yl.log_dict(result, "evaluation results")


# Window-level diagnostic keys: excluded from the generic datalist mean
# (a mean of per-recording stds is not a pooled spread, and a mean of
# n_windows is meaningless); the delta family is pooled properly below.
_WINDOW_DIAG_KEYS = frozenset({
    "n_windows", "esr_ssim_std", "bicubic_ssim_std",
    "ssim_delta_mean", "ssim_delta_std", "ssim_delta_pos_frac",
})


def aggregate_results(results: List[Dict[str, float]], names: List[str]):
    """Per-recording breakdown + datalist means (reference ``:336-347``).

    Window-level diagnostics (``n_windows``, SSIM spreads, the paired
    SSIM delta) are pooled across recordings weighted by window count —
    recovering the all-windows statistics exactly from per-recording
    (mean, std, n) — instead of being arithmetic-meaned like the metric
    columns."""
    breakdown: Dict[str, Dict[str, float]] = defaultdict(dict)
    means: Dict[str, List[float]] = defaultdict(list)
    for name, entry in zip(names, results):
        for k, v in entry.items():
            breakdown[k][name] = v
            if k not in _WINDOW_DIAG_KEYS:
                means[k].append(v)
    agg = {k: float(np.mean(v)) for k, v in means.items()}
    # datalist-level rmse re-derives from the datalist-mean mse (a mean of
    # per-recording rmse values would be Jensen-biased low again)
    _attach_rmse(agg)

    # pooled paired-SSIM-delta statistics over all windows of all
    # recordings: sum-of-squares reconstruction from per-recording
    # (mean, std, n); a recording with n=1 contributes its mean with zero
    # within-recording variance (exact)
    ns = [r.get("n_windows", 0.0) for r in results]
    total_n = float(sum(ns))
    if total_n:
        agg["n_windows"] = total_n
        have = [r for r in results
                if r.get("n_windows") and "ssim_delta_mean" in r]
        if have:
            pooled_mean = sum(
                r["n_windows"] * r["ssim_delta_mean"] for r in have
            ) / total_n
            agg["ssim_delta_mean"] = float(pooled_mean)
            agg["ssim_delta_pos_frac"] = float(sum(
                r["n_windows"] * r.get("ssim_delta_pos_frac", 0.0)
                for r in have
            ) / total_n)
            if total_n > 1:
                ss = sum(
                    (r["n_windows"] - 1) * r.get("ssim_delta_std", 0.0) ** 2
                    + r["n_windows"] * r["ssim_delta_mean"] ** 2
                    for r in have
                )
                var = (ss - total_n * pooled_mean ** 2) / (total_n - 1)
                agg["ssim_delta_std"] = float(np.sqrt(max(var, 0.0)))
    return dict(breakdown), agg


def run_inference(
    checkpoint_path: str,
    data_list: Sequence[str],
    output_path: str,
    dataset_config: Optional[Dict] = None,
    save_images: bool = True,
    lpips_backbone_npz: Optional[str] = None,
    allow_uncalibrated_lpips: bool = False,
    lpips_net: str = "alex",
    lpips_lin_npz: Optional[str] = None,
    engine: Optional[bool] = None,
    lanes: Optional[int] = None,
    chunk_windows: Optional[int] = None,
    compile_cache: Optional[bool] = None,
    precision: Optional[str] = None,
) -> Dict[str, float]:
    """Full driver: checkpoint -> model, datalist -> per-recording + mean
    reports under ``output_path`` (reference ``main`` mode 1, ``:295-347``).
    Returns the datalist-mean metrics.

    ``engine=True`` routes the datalist through the batched
    :class:`esr_tpu.inference.engine.StreamingEngine` (``lanes`` recordings
    per batch, ``chunk_windows`` scan-fused windows per dispatch,
    docs/INFERENCE.md) instead of the sequential per-window loop. The
    report files and their schema are identical; engine mode does not
    support LPIPS or image dumps (both need per-window host tensors).

    Each of the three knobs resolves explicit argument > the checkpoint
    config's ``inference`` block (the flagship recipes opt in there) >
    built-in default (sequential, 4 lanes, 8 fused windows)."""
    from esr_tpu.training.checkpoint import load_for_inference

    model, params, config = load_for_inference(checkpoint_path)
    # persistent XLA compile cache, resolved like the engine knobs:
    # explicit argument > the checkpoint config's trainer.compile_cache >
    # off. Enabled BEFORE any jit runs, so the per-checkpoint eval loops
    # the phase runners drive (one infer.py process per checkpoint, same
    # programs every time) stop paying the same compiles per process
    # (utils/xla_cache, docs/PERF.md "the serial tail").
    cc = (
        (config.get("trainer") or {}).get("compile_cache", False)
        if compile_cache is None else compile_cache
    )
    if cc:
        from esr_tpu.utils.xla_cache import enable_compile_cache

        enable_compile_cache(cc)
    inf_cfg = config.get("inference") or {}
    # one precision policy (esr_tpu.config.precision, satellite of the
    # bf16 ladder): CLI > the checkpoint's trainer.precision > f32 — a
    # checkpoint trained at bf16 infers at bf16 unless overridden, instead
    # of the engine silently ignoring the rung the model trained at
    from esr_tpu.config.precision import resolve_precision

    precision = resolve_precision(
        cli=precision,
        config=(config.get("trainer") or {}).get("precision"),
    )
    if engine is None:
        engine = bool(inf_cfg.get("engine", False))
    lanes = int(inf_cfg.get("lanes", 4) if lanes is None else lanes)
    chunk_windows = int(
        inf_cfg.get("chunk_windows", 8) if chunk_windows is None
        else chunk_windows
    )
    if dataset_config is None:
        dataset_config = config["valid_dataloader"]["dataset"]
    seqn = int(dataset_config["sequence"].get("seqn", 3))
    ck_seqn = config["model"].get("args", {}).get("num_frame", 3)
    assert ck_seqn == seqn, (
        f"checkpoint num_frame={ck_seqn} != dataloader seqn={seqn}"
    )  # reference infer_ours_cnt.py:125

    if engine:
        if lpips_backbone_npz is not None or allow_uncalibrated_lpips:
            raise ValueError(
                "engine mode does not support LPIPS (per-window host "
                "tensors); run sequential mode for LPIPS reports"
            )
        if save_images:
            logger.warning(
                "engine mode does not dump per-window images; "
                "--save_images ignored (use sequential mode for PNGs)"
            )
        from esr_tpu.inference.engine import StreamingEngine

        eng = StreamingEngine(
            model, params, seqn, lanes=lanes, chunk_windows=chunk_windows,
            precision=precision,
        )
        os.makedirs(output_path, exist_ok=True)
        results, names = eng.run_datalist(data_list, dataset_config)
        for result, name, data_path in zip(results, names, data_list):
            _write_recording_report(
                os.path.join(output_path, name), data_path,
                dataset_config, result,
            )
        breakdown, mean = aggregate_results(results, names)
        with YamlLogger(os.path.join(output_path, "inference_all.yml")) as yl:
            yl.log_info(f"inference {checkpoint_path} on {list(data_list)}")
            yl.log_dict(breakdown, "breakdown results for each data")
            yl.log_dict(mean, "mean results for the whole data")
        return mean

    lpips_model = lpips_params = None
    if lpips_backbone_npz is not None or allow_uncalibrated_lpips:
        from esr_tpu.losses.lpips import (
            LPIPS,
            load_backbone_npz,
            load_lpips_params,
        )

        backbone = (
            load_backbone_npz(lpips_backbone_npz)
            if lpips_backbone_npz
            else None
        )
        # net choice mirrors the reference DistModel (dist_model.py:45-74);
        # non-alex nets need their converted lin npz alongside the backbone
        lpips_model = LPIPS(net=lpips_net)
        lpips_params = load_lpips_params(
            backbone_state=backbone,
            net=lpips_net,
            lin_npz_path=lpips_lin_npz,
            allow_uncalibrated=allow_uncalibrated_lpips,
        )

    runner = InferenceRunner(
        model, params, seqn, lpips_model=lpips_model,
        lpips_params=lpips_params, precision=precision,
    )

    os.makedirs(output_path, exist_ok=True)
    results, names = [], []
    for data_path in data_list:
        name = os.path.basename(data_path)
        logger.info("processing %s", data_path)
        out_dir = os.path.join(output_path, name)
        result = runner.run_recording(
            data_path, dataset_config, out_dir, save_images=save_images
        )
        results.append(result)
        names.append(name)

    breakdown, mean = aggregate_results(results, names)
    with YamlLogger(os.path.join(output_path, "inference_all.yml")) as yl:
        yl.log_info(f"inference {checkpoint_path} on {list(data_list)}")
        yl.log_dict(breakdown, "breakdown results for each data")
        yl.log_dict(mean, "mean results for the whole data")
    return mean
