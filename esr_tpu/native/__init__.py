"""Native host kernels: lazy g++ build + ctypes bindings.

The TPU-native replacement for the reference's Cython data-path extensions
(``/root/reference/dataloader/cython_cnt2event``, ``cython_event_redistribute``,
``binary_search`` — built by its ``install.sh``): the hot host loops live in
``host_kernels.cpp``, compiled on first use into a per-machine cache and bound
via ctypes (no pybind11 in this image). Everything degrades gracefully — if no
compiler is available the numpy mirrors keep working and :func:`available`
returns False.

Set ``ESR_TPU_NATIVE=0`` to force the numpy path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "host_kernels.cpp")
_lib: Optional[ctypes.CDLL] = None
_tried = False

_F32 = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_I64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")


def _build() -> Optional[str]:
    cache_dir = os.path.join(
        os.path.expanduser("~"), ".cache", "esr_tpu_native"
    )
    os.makedirs(cache_dir, exist_ok=True)
    import hashlib

    tag = hashlib.sha1(open(_SRC, "rb").read()).hexdigest()[:16]
    so_path = os.path.join(cache_dir, f"host_kernels_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = [
        "g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
        _SRC, "-o", so_path + ".tmp",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(so_path + ".tmp", so_path)
        return so_path
    except Exception:
        # no OpenMP? retry without it
        try:
            cmd = [c for c in cmd if c != "-fopenmp"]
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(so_path + ".tmp", so_path)
            return so_path
        except Exception:
            return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("ESR_TPU_NATIVE", "1") == "0":
        return None
    so = _build()
    if so is None:
        return None
    lib = ctypes.CDLL(so)
    lib.rasterize_counts.argtypes = [
        _F32, _F32, _F32, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, _F32
    ]
    lib.rasterize_stack.argtypes = [
        _F32, _F32, _F32, _F32, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, _F32,
    ]
    lib.rescatter_counts.argtypes = [
        _F32, _F32, _F32, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, _F32
    ]
    lib.rasterize_counts_batch.argtypes = [
        _F32, _F32, _F32, _I64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, _F32,
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _c32(a) -> np.ndarray:
    return np.ascontiguousarray(a, np.float32)


def rasterize_counts(xs, ys, ps, sensor_size) -> Optional[np.ndarray]:
    """[H, W, 2] count image, or None when the native lib is unavailable."""
    lib = _load()
    if lib is None:
        return None
    h, w = sensor_size
    xs, ys, ps = _c32(xs), _c32(ys), _c32(ps)
    out = np.zeros((h, w, 2), np.float32)
    lib.rasterize_counts(xs, ys, ps, len(xs), h, w, out)
    return out


def rasterize_stack(xs, ys, ts, ps, num_bins, sensor_size) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    h, w = sensor_size
    xs, ys, ts, ps = _c32(xs), _c32(ys), _c32(ts), _c32(ps)
    out = np.zeros((h, w, num_bins), np.float32)
    lib.rasterize_stack(xs, ys, ts, ps, len(xs), num_bins, h, w, out)
    return out


def rescatter_counts(xs_norm, ys_norm, ps, sensor_size) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    h, w = sensor_size
    xs, ys, ps = _c32(xs_norm), _c32(ys_norm), _c32(ps)
    out = np.zeros((h, w, 2), np.float32)
    lib.rescatter_counts(xs, ys, ps, len(xs), h, w, out)
    return out


def rasterize_counts_batch(xs, ys, ps, offsets, sensor_size) -> Optional[np.ndarray]:
    """Concatenated events + ``offsets [items+1]`` -> [items, H, W, 2],
    OpenMP-parallel over items."""
    lib = _load()
    if lib is None:
        return None
    h, w = sensor_size
    xs, ys, ps = _c32(xs), _c32(ys), _c32(ps)
    offsets = np.ascontiguousarray(offsets, np.int64)
    items = len(offsets) - 1
    out = np.zeros((items, h, w, 2), np.float32)
    lib.rasterize_counts_batch(xs, ys, ps, offsets, items, h, w, out)
    return out
