// Native host kernels for the event data pipeline.
//
// The reference accelerates its host-side data path with Cython extensions
// (/root/reference/dataloader/cython_*) and rasterizes on torch DataLoader
// workers. The TPU-native equivalent keeps rasterization on the host CPU
// (dense tensors only cross to the device) but implements the hot loops in
// C++ with an extern "C" ABI consumed via ctypes — no pybind11 dependency.
//
// All kernels are single-pass, allocate nothing, and bounds-check the same
// way the numpy mirrors in esr_tpu/data/np_encodings.py do (out-of-range
// events dropped). Polarity weights are small integers, so float accumulation
// is exact and matches the numpy/bincount and jnp scatter-add paths bitwise.

#include <cmath>
#include <cstdint>

extern "C" {

// Two-channel count image: out[h][w][2], channel 0 = positive counts,
// channel 1 = negative counts (np_encodings.events_to_channels_np).
void rasterize_counts(const float* xs, const float* ys, const float* ps,
                      int64_t n, int64_t h, int64_t w, float* out) {
  for (int64_t i = 0; i < n; ++i) {
    // bounds checked on the FLOAT coordinates (numpy-mirror semantics: the
    // mask precedes the truncating cast, so -0.5 is dropped, not clamped)
    if (xs[i] < 0.f || xs[i] >= (float)w || ys[i] < 0.f || ys[i] >= (float)h)
      continue;
    const int64_t x = (int64_t)xs[i];
    const int64_t y = (int64_t)ys[i];
    const int64_t base = (y * w + x) * 2;
    if (ps[i] > 0.f) {
      out[base] += 1.f;
    } else if (ps[i] < 0.f) {
      out[base + 1] += 1.f;
    }
  }
}

// Signed time-binned stack: out[h][w][bins], half-open binning
// bin = floor((t - t0) / (t1 - t0 + 1e-6) * bins), clipped
// (np_encodings.events_to_stack_np).
void rasterize_stack(const float* xs, const float* ys, const float* ts,
                     const float* ps, int64_t n, int64_t bins, int64_t h,
                     int64_t w, float* out) {
  if (n == 0) return;
  float t0 = ts[0], t1 = ts[0];
  for (int64_t i = 1; i < n; ++i) {
    if (ts[i] < t0) t0 = ts[i];
    if (ts[i] > t1) t1 = ts[i];
  }
  const float dt = t1 - t0 + 1e-6f;
  for (int64_t i = 0; i < n; ++i) {
    if (xs[i] < 0.f || xs[i] >= (float)w || ys[i] < 0.f || ys[i] >= (float)h)
      continue;
    const int64_t x = (int64_t)xs[i];
    const int64_t y = (int64_t)ys[i];
    int64_t b = (int64_t)std::floor((ts[i] - t0) / dt * (float)bins);
    if (b < 0) b = 0;
    if (b >= bins) b = bins - 1;
    out[(y * w + x) * bins + b] += ps[i];
  }
}

// Fused renormalize-and-scatter: events with coordinates normalized to
// [0, 1) are scaled onto an (h, w) grid and count-rasterized in one pass —
// the SR input stream (dataset._scaled -> "cnt": coordinates multiplied by
// the target resolution, floored by the int cast, then scattered).
void rescatter_counts(const float* xs_norm, const float* ys_norm,
                      const float* ps, int64_t n, int64_t h, int64_t w,
                      float* out) {
  for (int64_t i = 0; i < n; ++i) {
    const float xf = xs_norm[i] * (float)w;
    const float yf = ys_norm[i] * (float)h;
    if (xf < 0.f || xf >= (float)w || yf < 0.f || yf >= (float)h) continue;
    const int64_t x = (int64_t)xf;
    const int64_t y = (int64_t)yf;
    const int64_t base = (y * w + x) * 2;
    if (ps[i] > 0.f) {
      out[base] += 1.f;
    } else if (ps[i] < 0.f) {
      out[base + 1] += 1.f;
    }
  }
}

// Batched count rasterization with per-item offsets, parallel over items.
// xs/ys/ps are the concatenation of all items' events; offsets[i]..offsets[i+1]
// delimit item i. out is [items][h][w][2], zero-initialized by the caller.
void rasterize_counts_batch(const float* xs, const float* ys, const float* ps,
                            const int64_t* offsets, int64_t items, int64_t h,
                            int64_t w, float* out) {
#pragma omp parallel for schedule(dynamic)
  for (int64_t it = 0; it < items; ++it) {
    rasterize_counts(xs + offsets[it], ys + offsets[it], ps + offsets[it],
                     offsets[it + 1] - offsets[it], h, w,
                     out + it * h * w * 2);
  }
}

}  // extern "C"
