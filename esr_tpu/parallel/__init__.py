from .mesh import make_mesh, shard_batch, replicate, make_parallel_train_step
