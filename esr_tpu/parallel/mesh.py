"""Device mesh + sharding: the distributed runtime, TPU-native.

Replaces the reference's DDP/NCCL stack (``train_ours_cnt_seq.py:64-85``
rendezvous, DDP gradient allreduce, ``DistributedSampler``) with JAX SPMD:

- a ``Mesh`` over all devices with a ``'data'`` axis (the model is a small
  CNN; DP is the parallelism that matters — SURVEY.md §2.3);
- batch sharded over ``'data'`` with ``NamedSharding``, params replicated;
- ``jit`` compiles ONE SPMD program; XLA inserts the gradient all-reduce
  over ICI automatically (no explicit collectives, no barriers — program
  structure is the synchronization);
- multi-host: the same code runs under ``jax.distributed.initialize`` where
  the mesh spans hosts and collectives ride ICI within a slice / DCN across
  slices. No rendezvous code needed here.

The explicit-logging allreduce (``reduce_tensor``, ``myutils/utils.py:43-54``)
has no equivalent: metrics computed inside the jit'd step are already
globally reduced.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    devices: Optional[Sequence] = None, axis_name: str = "data"
) -> Mesh:
    """1-D data-parallel mesh over all (or given) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), (axis_name,))


def shard_batch(batch: Any, mesh: Mesh, axis_name: str = "data") -> Any:
    """Place a host batch with the leading axis sharded over the mesh."""
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Fully replicate a pytree (params/opt state) over the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def make_parallel_train_step(
    train_step, mesh: Mesh, axis_name: str = "data", donate: bool = True
):
    """jit the train step with DP shardings pinned.

    ``state`` replicated, ``batch`` sharded on the leading (batch) axis,
    outputs replicated. XLA turns the gradient sum into an ICI all-reduce.
    """
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(axis_name))
    return jax.jit(
        train_step,
        in_shardings=(repl, data),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate else (),
    )
