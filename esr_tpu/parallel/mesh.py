"""Device mesh + sharding: the distributed runtime, TPU-native.

Replaces the reference's DDP/NCCL stack (``train_ours_cnt_seq.py:64-85``
rendezvous, DDP gradient allreduce, ``DistributedSampler``) with JAX SPMD:

- a ``Mesh`` over all devices with a ``'data'`` axis (the model is a small
  CNN; DP is the parallelism that matters — SURVEY.md §2.3);
- batch sharded over ``'data'`` with ``NamedSharding``, params replicated;
- ``jit`` compiles ONE SPMD program; XLA inserts the gradient all-reduce
  over ICI automatically (no explicit collectives, no barriers — program
  structure is the synchronization);
- multi-host: the same code runs under ``jax.distributed.initialize`` where
  the mesh spans hosts and collectives ride ICI within a slice / DCN across
  slices. No rendezvous code needed here.

The explicit-logging allreduce (``reduce_tensor``, ``myutils/utils.py:43-54``)
has no equivalent: metrics computed inside the jit'd step are already
globally reduced.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def honor_platform_env(infer_from_xla_flags: bool = False) -> None:
    """Make ``JAX_PLATFORMS=cpu <entry point>`` behave as documented.

    An installed TPU plugin ignores the env var, so apply it through
    ``jax.config`` (the authoritative path — see ``tests/conftest.py``)
    before the backend initializes. Shared by ``train.py`` / ``infer.py``
    / ``bench.py``; no-op when the var is unset.

    ``infer_from_xla_flags=True`` (dryrun-only — ``__graft_entry__``)
    additionally treats ``XLA_FLAGS=--xla_force_host_platform_device_count``
    as a CPU request: virtual host devices exist only on the CPU platform,
    and this must beat JAX_PLATFORMS — the image ships an ambient
    ``JAX_PLATFORMS=axon,cpu`` that is indistinguishable from an explicit
    setting, so deferring to the env var re-introduces the wedged-tunnel
    hang. (To dryrun on the real backend, unset XLA_FLAGS.) Kept opt-in so
    a leftover XLA_FLAGS export can never silently demote a real training /
    bench run to CPU.

    ``jax.config.update`` silently no-ops once a backend exists
    (jax 0.9.0), so when one is ALREADY initialized this verifies the
    active platform satisfies the request and raises on mismatch — never
    a silent run on the wrong platform. Backend initialization itself is
    never triggered here: ``train.py --multihost`` must reach
    ``jax.distributed.initialize`` with the backend still down."""
    import os

    if infer_from_xla_flags and (
        "xla_force_host_platform_device_count"
        in os.environ.get("XLA_FLAGS", "")
    ):
        plat = "cpu"
    else:
        plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)  # silent no-op post-init
        from jax._src import xla_bridge

        # Mismatch detection must never silently vanish on a jax upgrade
        # (ADVICE r4): resolve an introspection point fail-loud, preferring
        # the semi-public predicate over the private dict.
        if hasattr(xla_bridge, "backends_are_initialized"):
            already = xla_bridge.backends_are_initialized()
        elif hasattr(xla_bridge, "_backends"):
            already = bool(xla_bridge._backends)
        else:
            raise RuntimeError(
                "cannot determine whether a jax backend is already "
                "initialized (xla_bridge lost both backends_are_initialized"
                " and _backends on this jax version); refusing to continue "
                "without the 'never a silent run on the wrong platform' "
                "guarantee"
            )
        if already:
            # a backend predates the update, so the update had no effect;
            # acceptable only if the active one satisfies the request
            active = jax.default_backend()
            if active not in plat.split(","):
                raise RuntimeError(
                    f"backend already initialized as {active!r}; cannot "
                    f"honor the platform request for {plat!r}"
                )


def make_mesh(
    devices: Optional[Sequence] = None, axis_name: str = "data"
) -> Mesh:
    """1-D data-parallel mesh over all (or given) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), (axis_name,))


def shard_batch(batch: Any, mesh: Mesh, axis_name: str = "data") -> Any:
    """Place a host batch with the leading axis sharded over the mesh."""
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Fully replicate a pytree (params/opt state) over the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host glue: ``jax.distributed.initialize``.

    The reference's NCCL rendezvous reads ``RANK``/``WORLD_SIZE``/``MASTER_*``
    env vars (``train_ours_cnt_seq.py:64-85``); JAX reads the same class of
    launcher-provided env (or TPU metadata) inside ``initialize`` — call with
    no args on TPU pods / SLURM, or pass the triple explicitly. No-op when
    already initialized or when running single-process with no launcher env.
    """
    import jax.distributed

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # already initialized — keep going (idempotent launcher semantics)
        if "already initialized" not in str(e).lower():
            raise


def process_shard_info() -> tuple:
    """``(shard_id, num_shards)`` for the per-host data loader — the
    ``jax.process_index()`` replacement for torch's rank/world_size."""
    return jax.process_index(), jax.process_count()


def stage_batch(batch: Any, mesh: Mesh, axis_name: str = "data") -> Any:
    """Host-local numpy batch → global device array sharded over ``axis_name``.

    Single-process: a plain sharded ``device_put``. Multi-process: each host
    contributes its local shard of the global batch via
    ``jax.make_array_from_process_local_data`` (the per-host loader feeds
    ``global_batch / num_hosts`` rows; together they form the global array).
    """
    sharding = NamedSharding(mesh, P(axis_name))
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        batch,
    )


def stage_megabatch(megabatch: Any, mesh: Mesh, axis_name: str = "data") -> Any:
    """Host ``{key: (k, B, ...)}`` megabatch → global device arrays with the
    BATCH axis (axis 1) sharded over ``axis_name``.

    The k axis is the scan axis of :func:`esr_tpu.training.multistep.
    make_multi_step` — it stays unsharded (every device runs all k chained
    steps; the batch dim is what data-parallelism splits, exactly as in
    :func:`stage_batch`). Multi-process follows the same per-host-rows
    contract as ``stage_batch``, lifted one axis.
    """
    sharding = NamedSharding(mesh, P(None, axis_name))
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sharding), megabatch)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        megabatch,
    )


def make_parallel_multi_step(
    multi_step,
    mesh: Mesh,
    axis_name: str = "data",
    donate: bool = True,
    max_traces: int = 8,
):
    """jit a :func:`~esr_tpu.training.multistep.make_multi_step` super-step
    with DP shardings pinned: ``state`` (the donated scan carry — params,
    optimizer and recurrent state keep single-copy HBM residency through
    the k chained steps) replicated, the megabatch sharded on its BATCH
    axis (axis 1, matching :func:`stage_megabatch`), outputs replicated.

    Retrace-guarded like :func:`make_parallel_train_step`: the megabatch
    shape is ``(k, B, L, ...)`` and fully static per (k, loader) config —
    any retrace churn here is a shape leak in megabatch assembly.
    """
    from esr_tpu.analysis.retrace_guard import checked_jit

    repl = NamedSharding(mesh, P())
    mega = NamedSharding(mesh, P(None, axis_name))
    return checked_jit(
        multi_step,
        name="parallel_multi_step",
        max_traces=max_traces,
        in_shardings=(repl, mega),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate else (),
    )


def make_parallel_train_step(
    train_step,
    mesh: Mesh,
    axis_name: str = "data",
    donate: bool = True,
    max_traces: int = 8,
):
    """jit the train step with DP shardings pinned.

    ``state`` replicated, ``batch`` sharded on the leading (batch) axis,
    outputs replicated. XLA turns the gradient sum into an ICI all-reduce.

    Jitted through :func:`esr_tpu.analysis.retrace_guard.checked_jit`: a
    train step legitimately compiles a handful of times (shape families per
    loader epoch, bf16 vs f32 variants); past ``max_traces`` it is a
    recompilation storm from a shape/dtype leak in the input pipeline and
    the guard raises instead of silently burning the reservation.
    """
    from esr_tpu.analysis.retrace_guard import checked_jit

    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(axis_name))
    return checked_jit(
        train_step,
        name="parallel_train_step",
        max_traces=max_traces,
        in_shardings=(repl, data),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate else (),
    )
