"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no sequence parallelism at all — its sequence dimension is
a python loop on one device (SURVEY.md §2.3: "SP/CP: No") — but this
framework treats long-context as first-class: when sequences outgrow one
chip's HBM, shard the sequence axis over a mesh axis and compute attention
with XLA collectives over ICI.

Two standard strategies, both built on ``shard_map``:

- :func:`ring_attention` — blockwise attention with the K/V shards rotated
  around the ring via ``jax.lax.ppermute`` while a numerically-stable online
  softmax accumulates partial outputs (the Ring Attention construction:
  each device only ever holds ``seq/num_devices`` of K/V, memory is O(N/p)
  per device, and communication overlaps the ``seq²/p`` compute).
  Supports causal masking via global block offsets.
- :func:`ulysses_attention` — the all-to-all alternative: transpose the
  sharding from the sequence axis to the heads axis
  (``jax.lax.all_to_all``), run ordinary full attention on each device's
  head slice, transpose back. Cheaper comm at moderate lengths; requires
  ``num_heads % axis_size == 0``.

Both are exact: parity with single-device full attention is pinned by
``tests/test_context_parallel.py`` on the virtual 8-device CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6: public API with the `check_vma` kwarg
    from jax import shard_map
except ImportError:  # jax 0.4/0.5: experimental API, kwarg named `check_rep`
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_legacy(f, **kwargs)

Array = jax.Array


def _attention_block(
    q: Array,
    k: Array,
    v: Array,
    m: Array,
    l: Array,
    o: Array,
    mask: Optional[Array],
    scale: float,
):
    """One online-softmax update step.

    ``q [B, nq, H, D]``, ``k/v [B, nk, H, D]``; carries ``m`` (running max,
    [B, nq, H]), ``l`` (running denominator), ``o`` (unnormalized output).
    """
    scores = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    # guard fully-masked rows: keep m finite so exp() stays 0, not nan
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
    p = jnp.exp(scores - m_safe[..., None])
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum("bqhk,bkhd->bqhd", p, v)
    return m_new, l_new, o_new


def ring_attention(
    q: Array,
    k: Array,
    v: Array,
    mesh: Mesh,
    axis_name: str = "seq",
    causal: bool = False,
) -> Array:
    """Exact attention with the sequence axis sharded over ``axis_name``.

    ``q, k, v``: ``[B, N, H, D]`` global arrays (sharded or not — the
    ``shard_map`` in/out specs pin sequence sharding). Returns ``[B, N, H, D]``
    sharded the same way.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    axis_size = mesh.shape[axis_name]

    spec = P(None, axis_name, None, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    def inner(q_blk: Array, k_blk: Array, v_blk: Array) -> Array:
        b, nq, h, d = q_blk.shape
        nk = k_blk.shape[1]
        my_idx = jax.lax.axis_index(axis_name)

        m0 = jnp.full((b, nq, h), -jnp.inf, q_blk.dtype)
        l0 = jnp.zeros((b, nq, h), q_blk.dtype)
        o0 = jnp.zeros_like(q_blk)

        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

        def body(step, carry):
            k_cur, v_cur, m, l, o = carry
            # the K/V block currently held came from device (my_idx - step)
            src = (my_idx - step) % axis_size
            mask = None
            if causal:
                q_pos = my_idx * nq + jnp.arange(nq)
                k_pos = src * nk + jnp.arange(nk)
                mask = (
                    q_pos[None, :, None, None] >= k_pos[None, None, None, :]
                )
            m, l, o = _attention_block(q_blk, k_cur, v_cur, m, l, o, mask, scale)
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            return k_nxt, v_nxt, m, l, o

        _, _, m, l, o = jax.lax.fori_loop(
            0, axis_size, body, (k_blk, v_blk, m0, l0, o0)
        )
        return o / jnp.maximum(l, 1e-38)[..., None]

    return inner(q, k, v)


def ulysses_attention(
    q: Array,
    k: Array,
    v: Array,
    mesh: Mesh,
    axis_name: str = "seq",
    causal: bool = False,
) -> Array:
    """All-to-all (Ulysses) context parallelism: re-shard seq -> heads, run
    full attention per head shard, re-shard back."""
    axis_size = mesh.shape[axis_name]
    assert q.shape[2] % axis_size == 0, (
        f"num_heads {q.shape[2]} must divide by axis size {axis_size}"
    )
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)

    spec = P(None, axis_name, None, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    def inner(q_blk: Array, k_blk: Array, v_blk: Array) -> Array:
        # [B, N/p, H, D] -> all_to_all -> [B, N, H/p, D]
        def to_heads(x):
            return jax.lax.all_to_all(
                x, axis_name, split_axis=2, concat_axis=1, tiled=True
            )

        def to_seq(x):
            return jax.lax.all_to_all(
                x, axis_name, split_axis=1, concat_axis=2, tiled=True
            )

        qh, kh, vh = to_heads(q_blk), to_heads(k_blk), to_heads(v_blk)
        scores = jnp.einsum("bqhd,bkhd->bqhk", qh, kh) * scale
        if causal:
            n = qh.shape[1]
            mask = jnp.arange(n)[:, None] >= jnp.arange(n)[None, :]
            scores = jnp.where(mask[None, :, None, :], scores, -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bqhk,bkhd->bqhd", p, vh)
        return to_seq(out)

    return inner(q, k, v)


def full_attention(q: Array, k: Array, v: Array, causal: bool = False) -> Array:
    """Single-device reference: plain softmax attention ``[B, N, H, D]``."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
    if causal:
        n = q.shape[1]
        mask = jnp.arange(n)[:, None] >= jnp.arange(n)[None, :]
        scores = jnp.where(mask[None, :, None, :], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", p, v)
