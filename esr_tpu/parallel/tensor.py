"""Tensor parallelism: channel-dimension GSPMD sharding over a 2-D mesh.

The reference's only parallelism is NCCL data-parallel DDP
(``train_ours_cnt_seq.py:64-85``); this module exists because a TPU-native
framework expresses MODEL sharding as data placement and lets XLA/GSPMD
insert the collectives — there is no hand-written all-gather here, by
design. For DeepRecurrNet at its paper sizes (basech 8-32) TP is not
*profitable* — channel counts sit far below the MXU's 128 lanes — but the
mechanism is model-agnostic: any pytree whose leaves carry a trailing
channel axis shards the same way, so a wider family member (or the
``wide_model`` bench variant) picks it up unchanged.

Pipeline parallelism is deliberately NOT implemented: the flagship is
three small recurrent blocks; a pipeline's bubble + inter-stage transfer
overhead exceeds per-stage compute at every size this family reaches, and
SURVEY §2.3 identifies DP as the parallelism that matters. Expert
parallelism has no target (no MoE anywhere in the family).

Design:
- params / optimizer-state leaves whose trailing axis is divisible by the
  ``'model'`` mesh axis shard on it (conv kernels HWIO -> O, biases and
  norm scales ``(C,)`` -> C); everything else replicates;
- the train step jits with these shardings pinned on the state IN and
  OUT; the batch shards on ``'data'``;
- GSPMD inserts all-gathers / reduce-scatters wherever the program needs
  full channels. Exactness vs the replicated DP step is end-to-end tested
  (``tests/test_tensor_parallel.py``) and exercised in
  ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_tp_mesh(
    devices: Optional[Sequence] = None,
    data: int = 2,
    data_axis: str = "data",
    model_axis: str = "model",
) -> Mesh:
    """2-D ``(data, model)`` mesh; ``model`` gets ``len(devices) / data``."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % data != 0:
        raise ValueError(f"{n} devices do not split into data={data}")
    arr = np.array(devices).reshape(data, n // data)
    return Mesh(arr, (data_axis, model_axis))


def channel_shardings(
    tree: Any, mesh: Mesh, model_axis: str = "model"
) -> Any:
    """Per-leaf shardings: trailing-axis channel sharding where divisible.

    Leaves with ``ndim >= 1`` whose last axis is divisible by the model-
    axis size shard on it; scalars and indivisible leaves replicate. The
    rule is shape-driven so optimizer moments (same shapes as params)
    shard identically without any knowledge of the optimizer. A size-1
    model axis replicates everything rather than labelling every leaf
    'model'-sharded — the degeneracy guards in callers rely on the label
    meaning an actual split."""
    tp = mesh.shape[model_axis]

    def rule(leaf):
        shape = getattr(leaf, "shape", ())
        if tp > 1 and len(shape) >= 1 and shape[-1] % tp == 0 and shape[-1] >= tp:
            spec = [None] * (len(shape) - 1) + [model_axis]
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(rule, tree)


def make_tp_train_step(
    train_step,
    mesh: Mesh,
    state: Any = None,
    data_axis: str = "data",
    model_axis: str = "model",
    donate: bool = True,
    state_shardings: Any = None,
):
    """jit the train step with TP state shardings + DP batch sharding.

    Pass EITHER ``state`` (only inspected for leaf shapes, to build the
    sharding tree — use the same structure you will call the step with) OR
    a precomputed ``channel_shardings`` tree via ``state_shardings`` to
    reuse one tree across this, ``shard_state_tp`` and any caller-side
    planning. Outputs: state keeps its TP shardings, metrics replicate."""
    if state_shardings is not None:
        state_sh = state_shardings
    elif state is not None:
        state_sh = channel_shardings(state, mesh, model_axis)
    else:
        raise ValueError("pass state or state_shardings")
    batch_sh = NamedSharding(mesh, P(data_axis))
    repl = NamedSharding(mesh, P())
    return jax.jit(
        train_step,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,) if donate else (),
    )


def shard_state_tp(
    state: Any,
    mesh: Mesh,
    model_axis: str = "model",
    state_shardings: Any = None,
) -> Any:
    """Place a host/replicated state according to ``channel_shardings``
    (or a precomputed tree passed via ``state_shardings``)."""
    if state_shardings is None:
        state_shardings = channel_shardings(state, mesh, model_axis)
    return jax.tree.map(jax.device_put, state, state_shardings)
